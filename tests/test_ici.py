"""Read-once/ICI-scatter restore (ops/ici.py, io/scatter.py; docs/PERF.md
§7) on the virtual 8-host CPU mesh.

The pins the issue asked for: per-host NVMe traffic is <= 1/N of the
payload plus unit slack (the counters prove it), the served bytes are
bit-identical to the files, scatter-off is the untouched read-all stack,
and every failure mode — degraded engine, exchange error — browns out to
local full reads with zero consumer-visible errors (``ici_fallbacks``
counts each brown-out).
"""

import os

import jax
import numpy as np
import pytest

from nvme_strom_tpu.checkpoint import CheckpointManager, build_restore_manifest
from nvme_strom_tpu.io import StromEngine, wait_exact
from nvme_strom_tpu.io.scatter import ScatterStore, partition_files
from nvme_strom_tpu.ops import ici as ici_mod
from nvme_strom_tpu.ops.ici import IciExchange, scatter_engine
from nvme_strom_tpu.parallel.mesh import exchange_mesh
from nvme_strom_tpu.utils.config import EngineConfig
from nvme_strom_tpu.utils.stats import StromStats

UNIT = 1 << 16          # small partition unit so 8 hosts all get shares
N = 8


@pytest.fixture()
def engine():
    cfg = EngineConfig(chunk_bytes=1 << 20, queue_depth=8,
                       buffer_pool_bytes=8 << 20)
    with StromEngine(cfg, stats=StromStats()) as e:
        yield e


def _write_files(tmp_path, sizes, seed=0):
    rng = np.random.default_rng(seed)
    paths, datas = [], []
    for i, sz in enumerate(sizes):
        p = tmp_path / f"w{i}.safetensors"
        data = rng.integers(0, 256, size=sz, dtype=np.uint8)
        p.write_bytes(data.tobytes())
        paths.append(str(p))
        datas.append(data)
    return paths, datas


# -- partitioning ------------------------------------------------------


def test_partition_covers_every_byte_exactly_once():
    sizes = [1_000_000, 3_000, UNIT, 1, 5 * UNIT + 17]
    man = partition_files(sizes, N, UNIT)
    assert man.total_bytes == sum(sizes)
    assert sum(man.host_bytes) == sum(sizes)
    cover = [np.zeros(sz, np.int32) for sz in sizes]
    for h in range(N):
        for fi, off, ln in man.units_for(h):
            assert ln > 0 and off >= 0 and off + ln <= sizes[fi]
            assert off % UNIT == 0          # shares stay unit-aligned
            cover[fi][off:off + ln] += 1
    for c in cover:
        assert (c == 1).all()               # no gap, no overlap

def test_partition_balance_within_unit_slack():
    sizes = [1_000_000, 3_000, UNIT, 1, 5 * UNIT + 17]
    man = partition_files(sizes, N, UNIT)
    # each file hands out contiguous unit runs differing by at most one
    # unit between hosts, so the worst host carries at most one extra
    # unit per file over the even split
    assert max(man.host_bytes) <= sum(sizes) / N + len(sizes) * UNIT
    for h in range(N):
        assert sum(ln for _, _, ln in man.units_for(h)) \
            == man.host_bytes[h]


# -- the exchange ------------------------------------------------------


def test_exchange_roundtrip_unaligned_rows():
    ex = IciExchange(exchange_mesh(N))
    assert ex.n == N
    assert not ex._pallas_ok        # CPU mesh: lax degrade is THE path
    rng = np.random.default_rng(1)
    for row_bytes in (1, 4096, 12_345):
        rows = rng.integers(0, 256, size=(N, row_bytes), dtype=np.uint8)
        got = ex.all_gather(rows)
        assert got.shape == rows.shape
        assert np.array_equal(got, rows)


def test_exchange_rejects_bad_shape():
    ex = IciExchange(exchange_mesh(N))
    with pytest.raises(ValueError):
        ex.all_gather(np.zeros((N - 1, 64), np.uint8))


# -- scatter_engine: read-once + bit-identical serving -----------------


def test_scatter_serves_bit_identical_and_reads_one_nth(tmp_path,
                                                        engine):
    sizes = [1_000_000, 3_000, 7 * UNIT + 123]
    paths, datas = _write_files(tmp_path, sizes)
    served = scatter_engine(engine, paths, unit_bytes=UNIT)
    assert served is not None
    store = served.scatter_store

    # per-host flash traffic: <= 1/N of the payload + unit slack, and
    # the whole mesh reads each byte exactly once
    total = sum(sizes)
    assert sum(store.host_bytes_read.values()) == total
    for h, got in store.host_bytes_read.items():
        assert got <= total / N + len(sizes) * UNIT
    assert engine.stats.ici_bytes_read == total
    # single-process emulation has no peers: every byte came off this
    # host's own flash, so no interconnect savings are credited
    assert engine.stats.ici_bytes_received == 0
    assert engine.stats.ici_fallbacks == 0

    # reads crossing unit AND host-share boundaries serve bit-identical
    for fi, (off, ln) in [(0, (0, sizes[0])), (0, (UNIT - 9, 3 * UNIT)),
                          (1, (17, 2_000)), (2, (6 * UNIT, UNIT + 123))]:
        fh = served.open(paths[fi])
        with served.submit_read(fh, off, ln) as pend:
            got = np.asarray(pend.wait(10.0)).view(np.uint8).ravel()[:ln]
            assert np.array_equal(got, datas[fi][off:off + ln])
        served.close(fh)


def test_scatter_readv_mixes_store_hits_and_misses(tmp_path, engine):
    paths, datas = _write_files(tmp_path, [3 * UNIT, 2 * UNIT + 77])
    other = tmp_path / "outside.bin"
    other.write_bytes(bytes(range(256)) * 64)
    served = scatter_engine(engine, paths, unit_bytes=UNIT)
    assert served is not None
    fh0 = served.open(paths[0])
    fho = served.open(str(other))           # NOT in the scattered set
    reads = [(fh0, 0, 1000), (fho, 256, 512), (fh0, UNIT - 5, 100)]
    pends = served.submit_readv(reads, klass="restore")
    want = [datas[0][0:1000].tobytes(),
            other.read_bytes()[256:768],
            datas[0][UNIT - 5:UNIT + 95].tobytes()]
    for p, w in zip(pends, want):
        got = np.asarray(wait_exact(p)).view(np.uint8).tobytes()
        assert got == w
        p.release()
    served.close(fh0)
    served.close(fho)


def test_serve_engine_close_all_clears_handle_tracking(tmp_path,
                                                       engine):
    """``close_all`` must drop the fh→path map with the handles: a
    recycled fh integer naming a DIFFERENT file must never be served
    stale scattered-file bytes."""
    paths, _ = _write_files(tmp_path, [2 * UNIT])
    served = scatter_engine(engine, paths, unit_bytes=UNIT)
    served.open(paths[0])
    assert served._paths
    served.close_all()
    assert served._paths == {}


def test_scatter_store_view_outside_files_is_none(tmp_path, engine):
    paths, datas = _write_files(tmp_path, [2 * UNIT])
    served = scatter_engine(engine, paths, unit_bytes=UNIT)
    store = served.scatter_store
    assert store.view(paths[0], 0, 2 * UNIT + 1) is None   # past EOF
    assert store.view(str(tmp_path / "nope"), 0, 10) is None
    assert np.array_equal(store.view(paths[0], 5, 100), datas[0][5:105])


# -- brown-outs: every failure keeps the caller on read-all ------------


class _DegradedWrap:
    """Engine proxy whose supervisor reports an open breaker (and
    serves the brown-out path with buffered preads, like the real
    EngineSupervisor would)."""

    class _Sup:
        def __init__(self, inner):
            self._inner = inner

        def tick(self):
            pass

        def degraded(self):
            return True

        def serve_degraded(self, engine, spans, stats=None):
            from nvme_strom_tpu.io.health import DegradedRead
            return [DegradedRead(self._inner, fh, off, ln,
                                 getattr(engine, "stats", None))
                    for fh, off, ln in spans]

    def __init__(self, inner):
        self._inner = inner
        self.supervisor = self._Sup(inner)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_scatter_declines_on_degraded_engine(tmp_path, engine):
    paths, _ = _write_files(tmp_path, [2 * UNIT])
    served = scatter_engine(_DegradedWrap(engine), paths,
                            unit_bytes=UNIT)
    assert served is None                   # caller keeps plain engine
    assert engine.stats.ici_fallbacks == 1
    assert engine.stats.ici_bytes_read == 0


def test_scatter_rejects_corrupted_exchange(tmp_path, engine,
                                            monkeypatch):
    """A gather whose process/row mapping drifted (a locally-read row
    comes back altered) must brown out to read-all, never build a
    store that serves corrupt bytes."""
    paths, _ = _write_files(tmp_path, [2 * UNIT])
    real = ici_mod.IciExchange.all_gather

    def corrupt(self, rows):
        got = np.array(real(self, rows))
        got[0, 0] ^= 1
        return got

    monkeypatch.setattr(ici_mod.IciExchange, "all_gather", corrupt)
    served = scatter_engine(engine, paths, unit_bytes=UNIT)
    assert served is None
    assert engine.stats.ici_fallbacks == 1


def test_scatter_falls_back_on_exchange_failure(tmp_path, engine,
                                                monkeypatch):
    paths, _ = _write_files(tmp_path, [2 * UNIT])

    def boom(self, rows):
        raise RuntimeError("ici link down")

    monkeypatch.setattr(ici_mod.IciExchange, "all_gather", boom)
    served = scatter_engine(engine, paths, unit_bytes=UNIT)
    assert served is None
    assert engine.stats.ici_fallbacks == 1


# -- checkpoint restore under the env knob -----------------------------


def _state():
    rng = np.random.default_rng(7)
    return {"params": {
        "w": rng.standard_normal((64, 64)).astype(np.float32),
        "b": rng.standard_normal((4096,)).astype(np.float32)},
        "step": 3}


def _target():
    return {"params": {"w": np.zeros((64, 64), np.float32),
                       "b": np.zeros((4096,), np.float32)}, "step": 0}


def _assert_bitwise(got, want):
    for k in ("w", "b"):
        g = np.asarray(got["params"][k])
        assert g.dtype == want["params"][k].dtype
        assert np.array_equal(g, want["params"][k])  # bit-for-bit


def test_restore_scatter_on_is_bit_identical(tmp_path, engine,
                                             monkeypatch):
    state = _state()
    mgr = CheckpointManager(tmp_path / "ckpt", engine=engine)
    mgr.save(3, state)

    off = mgr.restore(_target())            # knob unset: read-all stack
    assert engine.stats.ici_bytes_read == 0
    assert engine.stats.ici_bytes_received == 0

    monkeypatch.setenv("STROM_ICI_SCATTER", "1")
    monkeypatch.setenv("STROM_ICI_UNIT_BYTES", str(UNIT))
    on = mgr.restore(_target())
    _assert_bitwise(on, state)
    _assert_bitwise(off, state)
    assert on["step"] == off["step"] == 3

    # the counters prove read-once: the mesh read the payload bytes
    # exactly once (vs N·total under read-all); received stays 0 in
    # single-process emulation — there are no peers to receive from
    man = build_restore_manifest(str(mgr.step_dir(3)), N, UNIT)
    assert engine.stats.ici_bytes_read == man.total_bytes
    assert engine.stats.ici_bytes_received == 0
    assert engine.stats.ici_fallbacks == 0
    for hb in man.host_bytes:
        assert hb <= man.total_bytes / N + len(man.paths) * UNIT


def test_restore_scatter_survives_exchange_failure(tmp_path, engine,
                                                   monkeypatch):
    """Breaker-open / link-down mid-restore: the consumer sees ZERO
    errors — restore browns out to local full reads and stays exact."""
    state = _state()
    mgr = CheckpointManager(tmp_path / "ckpt", engine=engine)
    mgr.save(3, state)
    monkeypatch.setenv("STROM_ICI_SCATTER", "1")

    def boom(self, rows):
        raise RuntimeError("ici link down")

    monkeypatch.setattr(ici_mod.IciExchange, "all_gather", boom)
    got = mgr.restore(_target())
    _assert_bitwise(got, state)
    assert engine.stats.ici_fallbacks >= 1


def test_restore_scatter_declines_on_degraded_engine(tmp_path,
                                                     monkeypatch):
    state = _state()
    cfg = EngineConfig(chunk_bytes=1 << 20, queue_depth=8,
                       buffer_pool_bytes=8 << 20)
    with StromEngine(cfg, stats=StromStats()) as inner:
        CheckpointManager(tmp_path / "ckpt", engine=inner).save(3, state)
        wrapped = _DegradedWrap(inner)
        mgr = CheckpointManager(tmp_path / "ckpt", engine=wrapped)
        monkeypatch.setenv("STROM_ICI_SCATTER", "1")
        got = mgr.restore(_target())
        _assert_bitwise(got, state)
        assert inner.stats.ici_fallbacks >= 1
        assert inner.stats.ici_bytes_read == 0   # local full read path


def test_restore_sharded_state_scatter_on(tmp_path, mesh8, engine,
                                          monkeypatch):
    """Sharded restore target (the real trainer shape) under scatter:
    device placement still follows the shardings, values stay exact."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    state = _state()
    mgr = CheckpointManager(tmp_path / "ckpt", engine=engine)
    mgr.save(3, state)
    monkeypatch.setenv("STROM_ICI_SCATTER", "1")
    monkeypatch.setenv("STROM_ICI_UNIT_BYTES", str(UNIT))
    sh_w = NamedSharding(mesh8, P("dp", None))
    sh_b = NamedSharding(mesh8, P())
    # restore honors the target leaves' own shardings
    target = {"params": {
        "w": jax.device_put(np.zeros((64, 64), np.float32), sh_w),
        "b": jax.device_put(np.zeros((4096,), np.float32), sh_b)},
        "step": 0}
    got = mgr.restore(target)
    _assert_bitwise(got, state)
    assert got["params"]["w"].sharding.is_equivalent_to(sh_w, 2)
    assert engine.stats.ici_bytes_read > 0


# -- weight streaming under the env knob -------------------------------


def test_weights_load_sharded_scatter_on(tmp_path, mesh8, engine,
                                         monkeypatch):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nvme_strom_tpu.formats import write_safetensors
    from nvme_strom_tpu.parallel.weights import LazyCheckpoint

    rng = np.random.default_rng(3)
    tensors = {"wte": rng.standard_normal((64, 32)).astype(np.float32),
               "bias": rng.standard_normal((32,)).astype(np.float32)}
    write_safetensors(tmp_path / "model.safetensors", tensors)
    sh = {"wte": NamedSharding(mesh8, P("dp", None)),
          "bias": NamedSharding(mesh8, P())}

    off = LazyCheckpoint(tmp_path).load_sharded(sh, engine=engine)
    monkeypatch.setenv("STROM_ICI_SCATTER", "1")
    monkeypatch.setenv("STROM_ICI_UNIT_BYTES", str(UNIT))
    on = LazyCheckpoint(tmp_path).load_sharded(sh, engine=engine)
    for k in tensors:
        assert np.array_equal(np.asarray(on[k]), tensors[k])
        assert np.array_equal(np.asarray(off[k]), np.asarray(on[k]))
    assert engine.stats.ici_bytes_read > 0
