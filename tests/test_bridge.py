"""Bridge tests: NVMe→device streaming correctness on the CPU backend.

The content-verification discipline mirrors the reference's ssd2gpu_test
(DMA bytes vs pread of the same range — SURVEY.md §4), with the device leg
included.
"""

import numpy as np
import pytest

from nvme_strom_tpu.io import StromEngine
from nvme_strom_tpu.ops import DeviceStream, write_from_device
from nvme_strom_tpu.utils.config import EngineConfig
from nvme_strom_tpu.utils.stats import StromStats


@pytest.fixture()
def engine():
    cfg = EngineConfig(chunk_bytes=1 << 20, queue_depth=8,
                       buffer_pool_bytes=16 << 20)
    with StromEngine(cfg, stats=StromStats()) as e:
        yield e


def test_stream_file_roundtrip(engine, tmp_data_file):
    path, payload = tmp_data_file
    ds = DeviceStream(engine, depth=3)
    got = b"".join(np.asarray(c).tobytes() for c in ds.stream_file(path))
    assert got == payload


def test_stream_file_device_resident(engine, tmp_data_file):
    import jax
    path, _ = tmp_data_file
    ds = DeviceStream(engine, depth=2)
    chunk = next(iter(ds.stream_file(path)))
    assert isinstance(chunk, jax.Array)
    assert chunk.dtype == np.uint8


def test_stream_ranges_ordering_and_shapes(engine, tmp_data_file):
    path, payload = tmp_data_file
    fh = engine.open(path)
    ranges = [(0, 1000), (500000, 2048), (7, 4096), (1 << 20, 128)]
    shapes = [None, (2, 1024), None, (128,)]
    ds = DeviceStream(engine, depth=2)
    outs = list(ds.stream_ranges(fh, ranges, shapes=shapes))
    engine.close(fh)
    assert len(outs) == 4
    for (off, ln), shp, out in zip(ranges, shapes, outs):
        arr = np.asarray(out)
        if shp:
            assert arr.shape == tuple(shp)
        assert arr.reshape(-1).tobytes() == payload[off:off + ln]


def test_read_to_device_whole_file(engine, tmp_data_file):
    path, payload = tmp_data_file
    ds = DeviceStream(engine, depth=2)
    arr = ds.read_to_device(path)
    assert np.asarray(arr).tobytes() == payload


def test_read_to_device_dtype_view(engine, tmp_data_file):
    path, payload = tmp_data_file
    ds = DeviceStream(engine)
    arr = ds.read_to_device(path, dtype=np.float32)
    expect = np.frombuffer(payload, dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(arr), expect)


def test_bytes_to_device_accounted(engine, tmp_data_file):
    path, payload = tmp_data_file
    ds = DeviceStream(engine)
    for _ in ds.stream_file(path):
        pass
    assert engine.stats.bytes_to_device == len(payload)


def test_early_close_releases_buffers(engine, tmp_data_file):
    """Abandoning a stream mid-way must return staging buffers to the pool."""
    path, payload = tmp_data_file
    ds = DeviceStream(engine, depth=4)
    it = ds.stream_file(path)
    next(it)
    it.close()  # triggers the generator's finally
    # all buffers must be free again: a full second pass succeeds
    got = b"".join(np.asarray(c).tobytes() for c in ds.stream_file(path))
    assert got == payload


def test_read_to_device_empty_file(engine, tmp_path):
    path = tmp_path / "empty.bin"
    path.write_bytes(b"")
    arr = DeviceStream(engine).read_to_device(path)
    assert arr.shape == (0,) and arr.dtype == np.uint8


def test_write_from_device_roundtrip(engine, tmp_path):
    import jax.numpy as jnp
    data = jnp.arange(1 << 18, dtype=jnp.int32)
    path = tmp_path / "dev.bin"
    n = write_from_device(engine, data, path)
    assert n == (1 << 18) * 4
    back = DeviceStream(engine).read_to_device(path, dtype=np.int32)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(data))


def test_write_from_device_larger_than_chunk(engine, tmp_path):
    """Arrays bigger than one staging buffer must be written chunked.
    Regression: 16 MiB write vs 1 MiB chunk_bytes raised EINVAL."""
    import jax.numpy as jnp
    data = jnp.arange(5 << 20, dtype=jnp.uint8).reshape(5, 1 << 20) % 251
    path = tmp_path / "big.bin"
    n = write_from_device(engine, data, path)
    assert n == 5 << 20
    assert path.read_bytes() == np.asarray(data).tobytes()


def test_stream_ready_drain_matches_blocking(engine, tmp_data_file):
    """drain='ready' (opportunistic is_ready retirement) must yield the
    identical ordered byte stream as the blocking policy — it only
    changes WHEN staging buffers recycle, never what comes out."""
    path, payload = tmp_data_file
    for depth in (1, 2, 5):
        ds = DeviceStream(engine, depth=depth, drain="ready")
        got = b"".join(np.asarray(c).tobytes()
                       for c in ds.stream_file(path))
        assert got == payload
    # arbitrary ranges keep order too
    fh = engine.open(path)
    ranges = [(4096, 8192), (0, 100), (1 << 20, 65536), (77, 4000)]
    ds = DeviceStream(engine, depth=3, drain="ready")
    outs = list(ds.stream_ranges(fh, ranges))
    engine.close(fh)
    for (off, ln), out in zip(ranges, outs):
        assert np.asarray(out).tobytes() == payload[off:off + ln]
    with pytest.raises(ValueError, match="drain"):
        DeviceStream(engine, drain="bogus")


def test_pjrt_cpu_alias_semantics():
    """The measured facts behind host_to_device's protective CPU copy
    (round-2 verdict #2: "a written answer on what PJRT does with the
    buffer" — the full answer is in ARCHITECTURE.md, this pins the
    observable half on the CPU client):

      - device_put of a >=64-byte-aligned numpy source ALIASES it
        (zero-copy): the jax.Array's buffer pointer equals the source's;
      - the alias is LIVE — mutating the numpy buffer mutates the
        "device" array, which is exactly why staging views (recycled on
        release()) must be copied before device_put on host-backed
        devices;
      - a misaligned source is copied (no alias), so the behavior is
        alignment-gated, and the engine pool's 4096-byte alignment
        always qualifies on the zero-copy side.
    """
    import jax

    buf = np.zeros(1 << 16, dtype=np.uint8)
    off = (-buf.ctypes.data) % 4096
    aligned = buf[off:off + 4096]
    arr = jax.device_put(aligned)
    ptr = arr.addressable_shards[0].data.unsafe_buffer_pointer()
    assert ptr == aligned.ctypes.data, "aligned source must alias"
    aligned[:] = 7                      # the hazard host_to_device guards
    assert int(np.asarray(arr)[0]) == 7, "alias is live"

    misaligned = buf[off + 3:off + 3 + 4096]
    arr2 = jax.device_put(misaligned)
    ptr2 = arr2.addressable_shards[0].data.unsafe_buffer_pointer()
    assert ptr2 != misaligned.ctypes.data, "misaligned source must copy"


def test_host_to_device_cpu_copy_is_alias_proof(engine, tmp_data_file):
    """host_to_device's CPU bounce copy makes the yielded array IMMUNE to
    staging recycling: stream a file, then scribble over the whole
    engine pool — every yielded array must still hash to the original
    payload.  (Without the copy, the aliased buffers would show the
    scribble — see test_pjrt_cpu_alias_semantics.)"""
    path, payload = tmp_data_file
    ds = DeviceStream(engine, depth=2)
    parts = list(ds.stream_file(path))
    # scribble: read DIFFERENT content through the same pool slots
    other = str(path) + ".other"
    with open(other, "wb") as f:
        f.write(bytes(len(payload)))
    list(DeviceStream(engine, depth=2).stream_file(other))
    got = b"".join(np.asarray(c).tobytes() for c in parts)
    assert got == payload


def test_staging_retire_pool_orders_and_bounds():
    """StagingRetirePool (deferred staging release, round-4): releases
    fire exactly once each, oldest-first, and pushing past ``depth``
    blocks on the oldest instead of growing without bound."""
    import jax.numpy as jnp
    from nvme_strom_tpu.ops.bridge import StagingRetirePool
    released = []
    pool = StagingRetirePool(depth=2)
    arrs = [jnp.arange(4) + i for i in range(4)]
    for i in range(4):
        pool.push(lambda i=i: released.append(i), [arrs[i]])
    # depth=2: at most 2 entries outstanding, so >= 2 retired already
    assert released == sorted(released) and len(released) >= 2
    pool.flush()
    assert released == [0, 1, 2, 3]
    pool.flush()                    # idempotent, nothing double-fires
    assert released == [0, 1, 2, 3]
    # None release: nothing tracked
    pool.push(None, [arrs[0]])
    pool.flush()
    assert released == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# Double-buffered host→HBM overlap stage (docs/PERF.md §6)
# ---------------------------------------------------------------------------

class _FakeTransfer:
    """Injectable transfer that records WHEN each slab's bytes are read
    vs when the slab is overwritten — the rotation-invariant probe.
    Returned arrays complete only when the test releases them."""

    def __init__(self):
        self.launched = []          # _FakeArray in launch order

    def __call__(self, host_view, dtype, shape):
        arr = _FakeArray(host_view)
        self.launched.append(arr)
        return arr


class _FakeArray:
    def __init__(self, host_view):
        self._src = host_view              # the slab slice it sources
        self.snapshot = host_view.copy()   # bytes at launch time
        self.nbytes = host_view.nbytes
        self.ready = False
        self.blocked = 0

    def block_until_ready(self):
        # the FIRST block is the completion moment: the slab must still
        # hold the launch-time bytes RIGHT NOW — an overwrite before
        # this is exactly the corruption the ping-pong gate prevents.
        # (Later blocks are after completion; the slab may legitimately
        # have been recycled by then.)
        if not self.ready:
            assert np.array_equal(self._src, self.snapshot), \
                "slab overwritten before its transfer completed"
            self.ready = True
        self.blocked += 1
        return self

    def is_ready(self):
        return self.ready


@pytest.mark.perf
def test_overlap_pingpong_slab_rotation(engine, tmp_data_file):
    """Slab k's next reuse blocks on the transfer it sourced; every
    chunk's device bytes equal the file bytes."""
    path, payload = tmp_data_file
    fake = _FakeTransfer()
    ds = DeviceStream(engine, depth=3, overlap=True,
                      overlap_transfer=fake)
    fh = engine.open(path)
    try:
        ranges = [(i << 20, 1 << 20) for i in range(6)]
        out = list(ds.stream_ranges(fh, ranges))
    finally:
        engine.close(fh)
    assert len(out) == 6
    for i, arr in enumerate(out):
        assert bytes(arr.snapshot) == payload[i << 20:(i + 1) << 20]
    # with two slabs and 6 chunks, chunks 2..5 each had to wait on the
    # transfer two slots earlier — every launched transfer was blocked
    # on before its slab was reused (the assertion inside _FakeArray
    # is the real check; this pins that it actually exercised)
    assert all(a.blocked >= 1 for a in fake.launched)
    assert engine.stats.overlap_chunks == 6
    assert engine.stats.overlap_bytes == 6 << 20


@pytest.mark.perf
def test_overlap_odd_tail_chunk(engine, tmp_data_file):
    """A tail shorter than the slab transfers exactly its bytes."""
    path, payload = tmp_data_file
    fake = _FakeTransfer()
    ds = DeviceStream(engine, depth=2, overlap=True,
                      overlap_transfer=fake)
    fh = engine.open(path)
    try:
        tail = 12_345
        ranges = [(0, 1 << 20), (1 << 20, tail)]
        out = list(ds.stream_ranges(fh, ranges))
    finally:
        engine.close(fh)
    assert out[1].nbytes == tail
    assert bytes(out[1].snapshot) == payload[1 << 20:(1 << 20) + tail]


@pytest.mark.perf
def test_overlap_verify_hook_runs_before_slab_copy(engine,
                                                   tmp_data_file):
    """Ordering contract: verify sees the staging view BEFORE the chunk
    touches a slab (a corrupt chunk never reaches a DMA slab), and a
    verify failure aborts the stream without leaking buffers."""
    path, _payload = tmp_data_file
    events = []

    def verify(ri, view):
        events.append(("verify", ri))
        if ri == 2:
            raise ValueError("synthetic corruption")

    def transfer(host_view, dtype, shape):
        events.append(("transfer", host_view.nbytes))
        a = _FakeArray(host_view)
        a.ready = True
        return a

    ds = DeviceStream(engine, depth=2, overlap=True,
                      overlap_transfer=transfer)
    fh = engine.open(path)
    try:
        with pytest.raises(ValueError, match="synthetic corruption"):
            list(ds.stream_ranges(fh, [(i << 20, 1 << 20)
                                       for i in range(4)],
                                  verify=verify))
    finally:
        engine.close(fh)
    # chunk 2 was verified but never transferred; order is strictly
    # verify-then-transfer per chunk
    assert ("verify", 2) in events
    transfers = [e for e in events if e[0] == "transfer"]
    assert len(transfers) == 2
    vi = [i for i, e in enumerate(events) if e[0] == "verify"]
    ti = [i for i, e in enumerate(events) if e[0] == "transfer"]
    assert all(v < t for v, t in zip(vi, ti))
    # no staging leak: the pool refills completely
    info = engine.pool_info()
    assert info["free_buffers"] == info["n_buffers"]


@pytest.mark.perf
def test_overlap_off_switch_bit_for_bit(engine, tmp_data_file,
                                        monkeypatch):
    """STROM_BRIDGE_OVERLAP=0 reproduces today's path exactly — same
    bytes, zero overlap counters — even on a stream built with
    overlap=True."""
    path, payload = tmp_data_file
    ranges = [(i << 20, 1 << 20) for i in range(4)]
    fh = engine.open(path)
    try:
        monkeypatch.setenv("STROM_BRIDGE_OVERLAP", "0")
        ds = DeviceStream(engine, depth=2, overlap=True)
        off = b"".join(np.asarray(a).tobytes()
                       for a in ds.stream_ranges(fh, ranges))
        assert engine.stats.overlap_chunks == 0
        assert engine.stats.overlap_bytes == 0
        monkeypatch.delenv("STROM_BRIDGE_OVERLAP")
        ds2 = DeviceStream(engine, depth=2, overlap=True)
        on = b"".join(np.asarray(a).tobytes()
                      for a in ds2.stream_ranges(fh, ranges))
        assert engine.stats.overlap_chunks == 4
    finally:
        engine.close(fh)
    assert off == on == payload[:4 << 20]


@pytest.mark.perf
def test_overlap_auto_gate_stays_off_on_cpu(engine, tmp_data_file):
    """overlap=None (auto) keeps the CPU fallback on the current
    device_put path — the overlap stage is a TPU-platform engagement."""
    path, payload = tmp_data_file
    ds = DeviceStream(engine, depth=2)          # overlap=None
    got = b"".join(np.asarray(a).tobytes()
                   for a in ds.stream_file(path))
    assert got == payload
    assert engine.stats.overlap_chunks == 0
