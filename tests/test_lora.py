"""LoRA (models/lora.py): zero-init identity, adapter-only training,
merged-decode equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from nvme_strom_tpu.models import lora
from nvme_strom_tpu.models.transformer import (
    TransformerConfig, init_params, loss_fn, tiny_config)


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig(**{**tiny_config().__dict__,
                               "dtype": jnp.float32})
    base = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab)
    return cfg, base, tokens


def test_zero_init_is_identity(setup):
    """B=0 → adapted model == base model exactly."""
    cfg, base, tokens = setup
    ad = lora.lora_init(jax.random.key(2), base, rank=4)
    want = loss_fn(base, tokens, cfg)
    got = lora.lora_loss_fn(ad, base, tokens, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6)
    merged = lora.merge_lora(base, ad)
    for n in base:
        np.testing.assert_array_equal(np.asarray(merged[n]),
                                      np.asarray(base[n]))


def test_adapter_training_reduces_loss_base_frozen(setup):
    """A few steps reduce loss; the base is bit-identical after."""
    cfg, base, tokens = setup
    ad = lora.lora_init(jax.random.key(3), base, rank=8)
    opt = optax.adam(1e-2)
    step = jax.jit(lora.make_lora_train_step(cfg, opt),
                   donate_argnums=(0, 1))
    opt_state = opt.init(ad)
    base_snapshot = jax.tree_util.tree_map(np.asarray, base)
    losses = []
    for _ in range(8):
        ad, opt_state, loss = step(ad, opt_state, base, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    for n in base:
        np.testing.assert_array_equal(np.asarray(base[n]),
                                      base_snapshot[n])
    # the trainable state is a small fraction of the base
    assert lora.count_params(ad) < 0.2 * lora.count_params(base)


def test_merged_params_decode(setup):
    """Merged params drive the existing generate() unchanged, and a
    trained adapter actually changes the output distribution."""
    from nvme_strom_tpu.models.decode import generate
    cfg, base, tokens = setup
    ad = lora.lora_init(jax.random.key(4), base, rank=4)
    # push B away from zero so the delta is nontrivial
    ad = jax.tree_util.tree_map(
        lambda x: x + 0.05 * jax.random.normal(jax.random.key(5),
                                               x.shape, x.dtype), ad)
    merged = lora.merge_lora(base, ad, alpha=8.0)
    prompt = tokens[:2, :8]
    out_base = np.asarray(generate(base, prompt, cfg, 8))
    out_ad = np.asarray(generate(merged, prompt, cfg, 8))
    assert out_ad.shape == out_base.shape
    assert (out_ad != out_base).any()


def test_targets_validation(setup):
    cfg, base, tokens = setup
    with pytest.raises(ValueError, match="no base matmuls"):
        lora.lora_init(jax.random.key(6), base, rank=4,
                       targets=("nonexistent",))
    with pytest.raises(ValueError, match="rank"):
        lora.lora_init(jax.random.key(7), base, rank=0)


def test_mlp_targets_opt_in(setup):
    cfg, base, tokens = setup
    ad = lora.lora_init(jax.random.key(8), base, rank=2,
                        targets=("wq", "w_gate", "w_down"))
    names = set(ad)
    assert any(n.endswith("w_gate") for n in names)
    assert any(n.endswith("w_down") for n in names)
    assert not any(n.endswith("wk") for n in names)


def test_lora_gradient_accumulation_matches(setup):
    """LoRA accum_steps produces the same adapters as full-batch."""
    cfg, base, tokens = setup

    def run(accum):
        ad = lora.lora_init(jax.random.key(9), base, rank=4)
        opt = optax.adam(1e-2)
        st = opt.init(ad)
        step = jax.jit(lora.make_lora_train_step(cfg, opt,
                                                 accum_steps=accum))
        for _ in range(3):
            ad, st, loss = step(ad, st, base, tokens)
        return ad, float(loss)

    a1, l1 = run(1)
    a2, l2 = run(2)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    for k in a1:
        for x, y in zip(a1[k], a2[k]):
            # atol 5e-6: accumulated vs full-batch grads legitimately
            # differ by float summation order (the grouped-attention
            # einsum layout shifted it just past 1e-6 on ~0.4% of
            # elements; the paths are still step-for-step equivalent)
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=5e-6, rtol=1e-5)


def test_lora_over_int8_base_trains():
    """QLoRA-style: adapters over an int8-quantized base — t=0 output
    equals the dequantized base exactly, a few steps reduce the loss,
    and the base stays int8 throughout (optimizer is adapter-sized)."""
    import optax
    from nvme_strom_tpu.models.quant import quantize_weights_int8
    from nvme_strom_tpu.models.transformer import forward

    cfg = TransformerConfig(**{**tiny_config().__dict__,
                               "dtype": jnp.float32})
    base = quantize_weights_int8(init_params(jax.random.key(0), cfg))
    adapters = lora.lora_init(jax.random.key(1), base, rank=4)
    assert "layers.0.wq" in adapters          # quantized leaves adapt
    toks = jax.random.randint(jax.random.key(2), (2, 16), 0, cfg.vocab,
                              dtype=jnp.int32)
    # t=0: merged == base (B is zero) — bf16 merge of the dequant
    l0 = forward(lora.merge_lora(base, adapters), toks, cfg)
    lb = forward(base, toks, cfg)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(lb),
                               atol=0.05, rtol=0.05)
    opt = optax.adam(3e-3)
    ostate = opt.init(adapters)
    step = jax.jit(lora.make_lora_train_step(cfg, opt))
    losses = []
    for _ in range(6):
        adapters, ostate, loss = step(adapters, ostate, base, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert isinstance(base["layers.0.wq"], dict)   # base untouched
