"""ORDER BY / LIMIT pushdown (sql/topk.py): streamed device-side top-k
merge vs pandas ground truth, WHERE composition, NULL semantics, and the
statistics-driven LIMIT elimination (skipped groups never read)."""

import numpy as np
import pytest

from nvme_strom_tpu.io import StromEngine
from nvme_strom_tpu.sql import ParquetScanner, sql_topk
from nvme_strom_tpu.utils.config import EngineConfig
from nvme_strom_tpu.utils.stats import StromStats


@pytest.fixture()
def engine():
    cfg = EngineConfig(chunk_bytes=1 << 20, queue_depth=8,
                       buffer_pool_bytes=8 << 20)
    with StromEngine(cfg, stats=StromStats()) as e:
        yield e


def _write(tmp_path, tbl, name="t.parquet", row_group_size=8192, **kw):
    import pyarrow.parquet as pq
    path = tmp_path / name
    pq.write_table(tbl, path, row_group_size=row_group_size, **kw)
    return path


@pytest.fixture()
def pq_file(tmp_path):
    import pyarrow as pa
    rng = np.random.default_rng(0)
    n = 50_000
    tbl = pa.table({
        "k": rng.integers(0, 37, n).astype(np.int32),
        "v": rng.standard_normal(n).astype(np.float32),
        "w": rng.integers(0, 1000, n).astype(np.int64),
    })
    return _write(tmp_path, tbl, compression="snappy"), tbl


def _expect(df, by, k, descending, cols):
    s = df.sort_values(by, ascending=not descending, kind="stable")
    return s.head(k)[cols]


@pytest.mark.parametrize("descending", [True, False])
@pytest.mark.parametrize("by,extra", [("v", ["k"]), ("w", ["v", "k"])])
def test_topk_matches_pandas(engine, pq_file, by, extra, descending):
    path, tbl = pq_file
    df = tbl.to_pandas()
    sc = ParquetScanner(path, engine)
    res = sql_topk(sc, by, columns=extra, k=25, descending=descending)
    exp = _expect(df, by, 25, descending, [by, *extra])
    # the ordered key column must match exactly (ties in OTHER columns
    # may legitimately resolve differently)
    np.testing.assert_array_equal(res[by], exp[by].to_numpy())
    # provenance: _row indexes the original table and re-reads the
    # same key values
    np.testing.assert_array_equal(
        df[by].to_numpy()[res["_row"]], res[by])
    assert len(res[by]) == 25


def test_topk_where_pushdown(engine, pq_file):
    path, tbl = pq_file
    df = tbl.to_pandas()
    sc = ParquetScanner(path, engine)
    res = sql_topk(sc, "v", columns=["w"], k=10,
                   where=lambda c: c["w"] < 100,
                   where_columns=["w"])
    assert (res["w"] < 100).all()
    exp = _expect(df[df["w"] < 100], "v", 10, True, ["v"])
    np.testing.assert_array_equal(res["v"], exp["v"].to_numpy())


def test_topk_where_ranges_prune_and_exact(engine, tmp_path):
    import pyarrow as pa
    # sorted key ⇒ tight per-group stats ⇒ provable pruning
    n = 40_000
    v = np.sort(np.arange(n, dtype=np.float32))
    tbl = pa.table({"v": v,
                    "x": np.arange(n, dtype=np.int32)})
    path = _write(tmp_path, tbl, row_group_size=4096)
    sc = ParquetScanner(path, engine)
    res = sql_topk(sc, "v", columns=["x"], k=5,
                   where_ranges=[("v", None, 999.0)])
    np.testing.assert_array_equal(
        res["v"], np.array([999, 998, 997, 996, 995], np.float32))
    assert (res["x"] == res["v"].astype(np.int32)).all()


def test_topk_limit_elimination_skips_groups(engine, tmp_path):
    import pyarrow as pa
    # 10 row groups, strictly increasing ⇒ DESC top-k lives entirely in
    # the last group; statistics order visits it first and the bound
    # check must eliminate the other 9 WITHOUT reading their payload
    n = 40_960
    tbl = pa.table({"v": np.arange(n, dtype=np.int64)})
    path = _write(tmp_path, tbl, row_group_size=4096)
    sc = ParquetScanner(path, engine)
    before = engine.stats.bytes_direct + engine.stats.bytes_fallback \
        + engine.stats.bounce_bytes
    res = sql_topk(sc, "v", k=7, descending=True)
    np.testing.assert_array_equal(
        res["v"], np.arange(n - 1, n - 8, -1, dtype=np.int64))
    assert res["_skipped_row_groups"] == 9
    # ascending flips which single group is read
    res2 = sql_topk(sc, "v", k=7, descending=False)
    np.testing.assert_array_equal(
        res2["v"], np.arange(0, 7, dtype=np.int64))
    assert res2["_skipped_row_groups"] == 9
    assert before < (engine.stats.bytes_direct
                     + engine.stats.bytes_fallback
                     + engine.stats.bounce_bytes)  # something was read


def test_topk_k_larger_than_survivors(engine, tmp_path):
    import pyarrow as pa
    tbl = pa.table({"v": np.arange(100, dtype=np.float32),
                    "w": np.arange(100, dtype=np.int32)})
    path = _write(tmp_path, tbl, row_group_size=32)
    sc = ParquetScanner(path, engine)
    res = sql_topk(sc, "v", k=50, where=lambda c: c["w"] >= 97,
                   where_columns=["w"])
    np.testing.assert_array_equal(res["v"],
                                  np.array([99, 98, 97], np.float32))


def test_topk_nan_keys_never_surface(engine, tmp_path):
    import pyarrow as pa
    v = np.array([1.0, np.nan, 3.0, np.nan, 2.0], np.float32)
    path = _write(tmp_path, pa.table({"v": v}), row_group_size=5)
    sc = ParquetScanner(path, engine)
    res = sql_topk(sc, "v", k=5)
    np.testing.assert_array_equal(res["v"],
                                  np.array([3, 2, 1], np.float32))


def test_topk_nulls_skip(engine, tmp_path):
    import pyarrow as pa
    v = pa.array([5.0, None, 3.0, 8.0, None, 1.0], pa.float32())
    w = pa.array([1, 2, None, 4, 5, 6], pa.int32())
    path = _write(tmp_path, pa.table({"v": v, "w": w}), row_group_size=3)
    sc = ParquetScanner(path, engine)
    # forbid (default) raises on NULLs
    with pytest.raises(ValueError, match="null"):
        sql_topk(sc, "v", columns=["w"], k=3)
    # skip: rows with ANY referenced NULL drop (v=3.0 has w NULL)
    res = sql_topk(sc, "v", columns=["w"], k=3, nulls="skip")
    np.testing.assert_array_equal(res["v"],
                                  np.array([8, 5, 1], np.float32))
    np.testing.assert_array_equal(res["w"], np.array([4, 1, 6], np.int32))


def test_topk_bad_args(engine, pq_file):
    path, _ = pq_file
    sc = ParquetScanner(path, engine)
    with pytest.raises(ValueError, match="k must be"):
        sql_topk(sc, "v", k=0)
    with pytest.raises(KeyError, match="nope"):
        sql_topk(sc, "nope", k=3)
    with pytest.raises(ValueError, match="nulls"):
        sql_topk(sc, "v", k=3, nulls="bogus")


def test_topk_fully_pruned_raises(engine, tmp_path):
    import pyarrow as pa
    tbl = pa.table({"v": np.arange(100, dtype=np.float32)})
    path = _write(tmp_path, tbl, row_group_size=50)
    sc = ParquetScanner(path, engine)
    with pytest.raises(ValueError, match="empty"):
        sql_topk(sc, "v", k=3, where_ranges=[("v", 1000.0, None)])


def test_topk_valid_sentinel_value_beats_filtered_rows(engine, tmp_path):
    """Regression: a VALID row whose key equals the invalid-row sentinel
    (-inf) must not lose its carry slot to WHERE-filtered rows, and
    filtered rows must never surface."""
    import pyarrow as pa
    v = np.array([-np.inf, 5.0, 7.0], np.float32)
    w = np.array([1, 0, 0], np.int32)
    path = _write(tmp_path, pa.table({"v": v, "w": w}), row_group_size=3)
    sc = ParquetScanner(path, engine)
    res = sql_topk(sc, "v", columns=["w"], k=2,
                   where=lambda c: c["w"] == 1, where_columns=["w"])
    np.testing.assert_array_equal(res["v"],
                                  np.array([-np.inf], np.float32))
    # variant: filtered row must not displace/surface among valid ones
    v2 = np.array([10.0, -np.inf, 5.0], np.float32)
    w2 = np.array([1, 1, 0], np.int32)
    path2 = _write(tmp_path, pa.table({"v": v2, "w": w2}),
                   name="t2.parquet", row_group_size=3)
    sc2 = ParquetScanner(path2, engine)
    res2 = sql_topk(sc2, "v", columns=["w"], k=2,
                    where=lambda c: c["w"] == 1, where_columns=["w"])
    np.testing.assert_array_equal(
        res2["v"], np.array([10.0, -np.inf], np.float32))
    assert (res2["w"] == 1).all()


def test_topk_int64_bounds_order_exactly(engine, tmp_path):
    """Regression: row-group visit order must compare int64 stat bounds
    exactly — 2^53 and 2^53+1 are equal as floats, and a float-cast sort
    could visit the smaller group first and eliminate the winner."""
    import jax
    import pyarrow as pa
    lo = np.full(4, 2**53, np.int64)
    hi = np.full(4, 2**53 + 1, np.int64)
    # x64 ON: without it device arrays narrow to int32 and 2^53 cannot
    # even be represented — the bound ordering under test is about
    # full-width keys by construction
    with jax.enable_x64(True):
        for first, second in ((lo, hi), (hi, lo)):  # both physical orders
            tbl = pa.table({"v": np.concatenate([first, second])})
            path = _write(tmp_path, tbl, name="t53.parquet",
                          row_group_size=4)
            sc = ParquetScanner(path, engine)
            res = sql_topk(sc, "v", k=4, descending=True)
            assert (res["v"] == 2**53 + 1).all(), res["v"]
