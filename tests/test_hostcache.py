"""Tiered pinned-host DRAM cache (io/hostcache.py — docs/PERF.md §4).

Hardware-free (`-m perf` rides along with the planner smoke): unit
tests drive the HostCache directly (admission ghost list, class
quotas + eviction exactness, write invalidation); planner tests prove
the hit/miss splitting through a real StromEngine on tmp files — full
hits, head/tail hits with a middle miss, line-boundary straddles, hit
spans bypassing FaultyEngine/ResilientEngine entirely, and
``STROM_HOSTCACHE_MB=0`` restoring the exact pre-tier path.
"""

import os

import numpy as np
import pytest

from nvme_strom_tpu.io import StromEngine, plan_and_submit, wait_exact
from nvme_strom_tpu.io import hostcache
from nvme_strom_tpu.io.hostcache import HostCache
from nvme_strom_tpu.io.plan import submit_spans_tiered
from nvme_strom_tpu.utils.config import EngineConfig, HostCacheConfig
from nvme_strom_tpu.utils.stats import StromStats

LINE = 64 << 10


def _cfg(**kw):
    base = dict(chunk_bytes=1 << 20, queue_depth=8,
                buffer_pool_bytes=16 << 20, n_rings=1)
    base.update(kw)
    return EngineConfig(**base)


@pytest.fixture()
def tier():
    """Process tier pinned to a small deterministic geometry; torn down
    so other tests see the env-derived (disabled) default again."""
    cache = hostcache.configure(HostCacheConfig(
        budget_mb=1, line_bytes=LINE))   # 16 lines of 64 KiB
    yield cache
    hostcache.reset()


@pytest.fixture()
def data_file(tmp_path):
    payload = np.random.default_rng(13).integers(
        0, 256, 1 << 20, dtype=np.uint8).tobytes()
    path = tmp_path / "hc.bin"
    path.write_bytes(payload)
    return str(path), payload


@pytest.fixture()
def engine():
    stats = StromStats()
    eng = StromEngine(_cfg(), stats=stats)
    yield eng
    eng.close_all()


def _warm(cache, engine, fh, extents, klass=None):
    """Two passes: ghost-note, then admit+fill (the admission dance)."""
    for _ in range(2):
        for pieces in plan_and_submit(engine, extents,
                                      chunk_bytes=256 << 10, klass=klass):
            for p in pieces:
                p.wait()
                p.release()


def _read(engine, extents, klass=None):
    out = []
    views = plan_and_submit(engine, extents, chunk_bytes=256 << 10,
                            klass=klass)
    for pieces in views:
        out.append(b"".join(bytes(wait_exact(p)) for p in pieces))
        for p in pieces:
            p.release()
    return out, views


# ------------------------------------------------------------- unit: cache

@pytest.mark.perf
def test_ghost_list_admission_refuses_first_touch(tier):
    fkey = ("f", 1)
    segs, admitted = tier.probe_range(fkey, 0, LINE, None)
    assert segs == [("miss", 0, LINE)]
    assert admitted == {}                 # one-shot scan: not admitted
    segs, admitted = tier.probe_range(fkey, 0, LINE, None)
    assert set(admitted) == {(fkey, 0)}   # second touch: admitted
    assert tier.fill(fkey, 0, np.zeros(LINE, np.uint8), None)
    segs, _ = tier.probe_range(fkey, 0, LINE, None)
    assert segs[0][0] == "hit"
    tier.unpin(segs[0][3])


@pytest.mark.perf
def test_partial_prefix_line_upgrades_on_a_longer_read(tier):
    """A resident-but-short line must not pin its slot while the full
    line misses forever: a longer read's probe admits the extension."""
    fkey = ("f", 8)
    assert tier.fill(fkey, 0, np.zeros(LINE // 2, np.uint8), None)
    segs, admitted = tier.probe_range(fkey, 0, LINE, None)
    assert segs == [("miss", 0, LINE)]
    assert set(admitted) == {(fkey, 0)}   # resident line → extend
    assert tier.fill(fkey, 0, np.zeros(LINE, np.uint8), None,
                     epoch=admitted[(fkey, 0)])
    segs, _ = tier.probe_range(fkey, 0, LINE, None)
    assert segs[0][0] == "hit"
    tier.unpin(segs[0][3])


@pytest.mark.perf
def test_write_completion_bumps_epoch_again(tier, data_file, engine):
    """The staleness guard fires at write SUBMIT and COMPLETION: a read
    admitted between the two (which may complete with pre-write bytes)
    is voided by the second bump."""
    path, _payload = data_file
    fh = engine.open(path, writable=True)
    fkey = engine.file_key(fh)
    w = engine.submit_write(fh, 0, np.zeros(LINE, np.uint8))
    e_submit = tier._key_epoch.get((fkey, 0), 0)
    assert e_submit >= 1
    w.wait()
    assert tier._key_epoch.get((fkey, 0), 0) > e_submit
    # the guard is per line: other offsets of the file are untouched
    assert (fkey, 4 * LINE) not in tier._key_epoch
    engine.close(fh)


@pytest.mark.perf
def test_epoch_map_eviction_fails_closed(tier):
    """Losing a write's epoch entry to the bounded map must REFUSE a
    pre-write fill (floor semantics), never re-admit it as epoch 0."""
    fkey = ("f", 11)
    tier.probe_range(fkey, 0, LINE, None)
    _, admitted = tier.probe_range(fkey, 0, LINE, None)
    epoch0 = admitted[(fkey, 0)]
    assert epoch0 == 0                        # never-written key
    tier.invalidate(fkey, 0, 1)               # the write
    # force the bounded map to drop the write's entry
    tier._key_epoch_cap = 0
    tier.invalidate(("other", 1), 0, 1)       # triggers the trim
    assert (fkey, 0) not in tier._key_epoch
    assert tier._epoch_floor >= 1
    assert not tier.fill(fkey, 0, np.zeros(LINE, np.uint8), None,
                         epoch=epoch0)        # refused, not re-admitted


@pytest.mark.perf
def test_consumer_checksum_failure_spoils_the_filled_line(tier,
                                                          data_file,
                                                          engine):
    """The PR 5 heal protocol must not re-read a corrupt FILL from the
    tier: check_with_reread's spoil hook drops the line, so the re-read
    reaches the device and heals."""
    from nvme_strom_tpu.io.hostcache import spoil_span
    from nvme_strom_tpu.utils.checksum import VerifyPolicy, crc32c
    path, payload = data_file
    fh = engine.open(path)
    _warm(tier, engine, fh, [(fh, 0, LINE)])
    assert tier.bytes_resident >= LINE
    # simulate a transiently corrupt fill: flip a byte in the resident
    # line (the stamp below is over the TRUE file bytes)
    fkey = engine.file_key(fh)
    line = tier._lines[(fkey, 0)]
    tier.arena.view[line.slot * tier.line_bytes] ^= 0xFF
    got, _ = _read(engine, [(fh, 0, LINE)])
    assert got[0] != payload[:LINE]           # the tier serves corruption
    policy = VerifyPolicy(mode="full")
    healed = policy.check_with_reread(
        np.frombuffer(got[0], np.uint8), crc32c(payload[:LINE]),
        lambda: _read(engine, [(fh, 0, LINE)])[0][0],
        engine.stats, where="spoil test",
        spoil=lambda: spoil_span(engine, fh, 0, LINE, engine.stats))
    assert bytes(healed) == payload[:LINE]    # re-read hit the device
    engine.close(fh)


@pytest.mark.perf
def test_write_between_admission_and_fill_voids_the_fill(tier):
    """A fill whose admission verdict predates a write to the file is
    refused — a miss read racing a write can never install pre-write
    bytes as a resident line."""
    fkey = ("f", 9)
    tier.probe_range(fkey, 0, LINE, None)             # ghost note
    _, admitted = tier.probe_range(fkey, 0, LINE, None)
    (key, epoch), = admitted.items()
    tier.invalidate(fkey, 0, 1)                        # the racing write
    assert not tier.fill(fkey, 0, np.zeros(LINE, np.uint8), None,
                         epoch=epoch)
    assert tier.bytes_resident == 0
    # the written line re-earns admission from scratch (the write also
    # cleared its ghost entry), then fills normally under the new epoch
    _, admitted = tier.probe_range(fkey, 0, LINE, None)
    assert admitted == {}                 # first touch again, by design
    _, admitted = tier.probe_range(fkey, 0, LINE, None)
    assert tier.fill(fkey, 0, np.zeros(LINE, np.uint8), None,
                     epoch=admitted[key])


@pytest.mark.perf
def test_eviction_falls_back_past_pinned_over_quota_class():
    """When every over-quota line is pinned, pressure reclaims from an
    under-quota class instead of refusing the fill."""
    cache = HostCache(line_bytes=LINE, budget_bytes=4 * LINE,
                      quotas={"decode": 1.0, "prefetch": 1.0})
    try:
        fkey = ("f", 10)
        for i in range(3):   # decode over its 2-slot quota
            cache.fill(fkey, i * LINE, np.zeros(LINE, np.uint8), "decode")
        cache.fill(fkey, 3 * LINE, np.zeros(LINE, np.uint8), "prefetch")
        pins = []
        for i in range(3):   # pin ALL decode lines
            segs, _ = cache.probe_range(fkey, i * LINE, LINE, None)
            pins.append(segs[0][3])
        assert cache.fill(fkey, 9 * LINE, np.zeros(LINE, np.uint8),
                          "prefetch")   # evicts the unpinned prefetch line
        assert cache.bytes_resident == 4 * LINE
        for line in pins:
            cache.unpin(line)
    finally:
        cache.close()


@pytest.mark.perf
def test_partial_line_prefix_serves_only_valid_bytes(tier):
    fkey = ("f", 2)
    assert tier.fill(fkey, 0, np.zeros(100, np.uint8), None)
    # inside the prefix: hit; past it: miss
    segs, _ = tier.probe_range(fkey, 0, 100, None)
    assert segs[0][0] == "hit"
    tier.unpin(segs[0][3])
    segs, _ = tier.probe_range(fkey, 0, 200, None)
    assert [s[0] for s in segs] == ["miss"]


@pytest.mark.perf
def test_eviction_under_quota_pressure_keeps_bytes_resident_exact():
    cache = HostCache(line_bytes=LINE, budget_bytes=4 * LINE,
                      quotas={"decode": 1.0, "prefetch": 1.0})
    try:
        stats = StromStats()
        fkey = ("f", 3)
        # decode grows past its 2-line quota into free space (borrowing)
        for i in range(4):
            assert cache.fill(fkey, i * LINE,
                              np.full(LINE, i, np.uint8), "decode", stats)
        assert cache.bytes_resident == 4 * LINE
        # prefetch pressure: the over-quota decode class pays, exactly
        # one line per fill, and the ledger stays exact throughout
        for i in range(4, 6):
            assert cache.fill(fkey, i * LINE,
                              np.full(LINE, i, np.uint8), "prefetch",
                              stats)
            resident = sum(ln.valid for ln in cache._lines.values())
            assert cache.bytes_resident == resident == 4 * LINE
        assert stats.cache_evictions == 2
        assert cache.counters()["class_slots"]["prefetch"] == 2
        # pinned lines are never reclaimed: pin everything, next fill
        # is refused rather than corrupting a held view
        pins = []
        for key in list(cache._lines):
            segs, _ = cache.probe_range(key[0], key[1], LINE, None)
            pins.append(segs[0][3])
        assert not cache.fill(fkey, 99 * LINE, np.zeros(LINE, np.uint8),
                              "prefetch", stats)
        for line in pins:
            cache.unpin(line)
    finally:
        cache.close()


@pytest.mark.perf
def test_write_invalidation_drops_overlapping_lines(tier):
    fkey = ("f", 4)
    stats = StromStats()
    for i in range(3):
        tier.fill(fkey, i * LINE, np.zeros(LINE, np.uint8), None, stats)
    assert tier.bytes_resident == 3 * LINE
    n = tier.invalidate(fkey, LINE + 7, 1, stats=stats)
    assert n == 1
    assert tier.bytes_resident == 2 * LINE
    assert stats.cache_invalidations == 1
    segs, _ = tier.probe_range(fkey, LINE, LINE, None)
    assert segs[0][0] == "miss"


@pytest.mark.perf
def test_checksum_mismatch_drops_line_and_heals_as_miss(tier):
    from nvme_strom_tpu.utils.checksum import VerifyPolicy
    cache = HostCache(line_bytes=LINE, budget_bytes=4 * LINE,
                      verify=VerifyPolicy(mode="full"))
    try:
        stats = StromStats()
        fkey = ("f", 5)
        cache.fill(fkey, 0, np.zeros(LINE, np.uint8), None, stats)
        line = cache._lines[(fkey, 0)]
        cache.arena.view[line.slot * LINE] ^= 0xFF   # flip a resident bit
        segs, _ = cache.probe_range(fkey, 0, LINE, None, stats)
        assert segs[0][0] == "miss"                  # dropped, not served
        assert stats.checksum_failures == 1
        assert cache.bytes_resident == 0
    finally:
        cache.close()


# ------------------------------------------------- planner hit/miss split

@pytest.mark.perf
def test_extent_fully_cached_serves_zero_copy_hits(tier, data_file,
                                                   engine):
    path, payload = data_file
    fh = engine.open(path)
    exts = [(fh, 0, 2 * LINE)]
    _warm(tier, engine, fh, exts)
    before = engine.engine_stats()["requests_submitted"]
    got, views = _read(engine, exts)
    assert got[0] == payload[:2 * LINE]
    # one zero-copy piece per line, nothing submitted to the engine
    assert len(views[0]) == 2
    assert engine.engine_stats()["requests_submitted"] == before
    assert engine.stats.bytes_served_cache == 2 * LINE
    engine.close(fh)


@pytest.mark.perf
def test_head_tail_cached_middle_miss(tier, data_file, engine):
    path, payload = data_file
    fh = engine.open(path)
    fkey = engine.file_key(fh)
    # resident head and tail lines; the middle line stays cold
    with open(path, "rb") as f:
        raw = f.read()
    tier.fill(fkey, 0, np.frombuffer(raw[:LINE], np.uint8), None)
    tier.fill(fkey, 2 * LINE,
              np.frombuffer(raw[2 * LINE:3 * LINE], np.uint8), None)
    exts = [(fh, 0, 3 * LINE)]
    before = engine.engine_stats()["requests_submitted"]
    got, views = _read(engine, exts)
    assert got[0] == payload[:3 * LINE]
    kinds = [type(p).__name__ for p in views[0]]
    assert kinds == ["CacheHitRead", "SpanView", "CacheHitRead"]
    # exactly the middle line went to the device
    assert engine.engine_stats()["requests_submitted"] == before + 1
    engine.close(fh)


@pytest.mark.perf
def test_line_boundary_straddles(tier, data_file, engine):
    path, payload = data_file
    fh = engine.open(path)
    fkey = engine.file_key(fh)
    with open(path, "rb") as f:
        raw = f.read()
    tier.fill(fkey, 0, np.frombuffer(raw[:LINE], np.uint8), None)
    # [32K, 96K) straddles resident line 0 and cold line 1
    a, b = LINE // 2, LINE // 2 + LINE
    got, views = _read(engine, [(fh, a, b - a)])
    assert got[0] == payload[a:b]
    assert [type(p).__name__ for p in views[0]] == ["CacheHitRead",
                                                    "SpanView"]
    # both lines resident: the same straddle becomes two hit pieces
    tier.fill(fkey, LINE, np.frombuffer(raw[LINE:2 * LINE], np.uint8),
              None)
    got, views = _read(engine, [(fh, a, b - a)])
    assert got[0] == payload[a:b]
    assert [type(p).__name__ for p in views[0]] == ["CacheHitRead",
                                                    "CacheHitRead"]
    engine.close(fh)


@pytest.mark.perf
def test_hit_spans_never_enter_faulty_or_resilient(tier, data_file):
    """A fully-cached extent must succeed even when EVERY engine read
    fails: the hit path goes straight to the arena, below no wrapper."""
    from nvme_strom_tpu.io import FaultPlan, FaultyEngine, ResilientEngine
    from nvme_strom_tpu.io.resilient import ReadError
    from nvme_strom_tpu.utils.config import ResilientConfig
    path, payload = data_file
    stats = StromStats()
    base = StromEngine(_cfg(), stats=stats)
    try:
        fh = base.open(path)
        fkey = base.file_key(fh)
        with open(path, "rb") as f:
            raw = f.read()
        tier.fill(fkey, 0, np.frombuffer(raw[:LINE], np.uint8), None)
        eng = ResilientEngine(
            FaultyEngine(base, FaultPlan.parse("eio:p=1.0", seed=1)),
            config=ResilientConfig(max_retries=0, backoff_base_s=0.0,
                                   hedging=False))
        (pieces,) = plan_and_submit(eng, [(fh, 0, LINE)],
                                    chunk_bytes=256 << 10)
        assert bytes(wait_exact(pieces[0])) == payload[:LINE]
        for p in pieces:
            p.release()
        # the cold neighbor goes through the wrappers and DOES fail —
        # proof the fault plan was live while the hit sailed past it
        (pieces,) = plan_and_submit(eng, [(fh, LINE, LINE)],
                                    chunk_bytes=256 << 10)
        with pytest.raises(ReadError):
            pieces[0].wait()
        for p in pieces:
            p.release()
        base.close(fh)
    finally:
        base.close_all()


@pytest.mark.perf
def test_fill_on_miss_after_admission(tier, data_file, engine):
    path, payload = data_file
    fh = engine.open(path)
    exts = [(fh, 0, LINE)]
    _warm(tier, engine, fh, exts)      # pass 1 ghost, pass 2 fill
    assert engine.stats.cache_admissions >= 1
    assert tier.bytes_resident >= LINE
    got, _ = _read(engine, exts)       # pass 3: a hit
    assert got[0] == payload[:LINE]
    assert engine.stats.cache_hits >= 1
    engine.close(fh)


@pytest.mark.perf
def test_engine_write_invalidates_through_the_tier(tier, data_file,
                                                   engine):
    path, payload = data_file
    fh = engine.open(path, writable=True)
    exts = [(fh, 0, LINE)]
    _warm(tier, engine, fh, exts)
    new = np.random.default_rng(5).integers(0, 256, LINE, dtype=np.uint8)
    engine.submit_write(fh, 0, new).wait()
    assert engine.stats.cache_invalidations == 1
    got, _ = _read(engine, exts)
    assert got[0] == new.tobytes()     # never the stale cached bytes
    engine.close(fh)


@pytest.mark.perf
def test_stream_span_path_hits_single_line_spans(tier, data_file,
                                                 engine):
    path, payload = data_file
    fh = engine.open(path)
    spans = [(fh, 0, LINE), (fh, 2 * LINE, LINE // 2)]
    for _ in range(2):                 # ghost, then fill
        for pr in submit_spans_tiered(engine, spans):
            pr.wait()
            pr.release()
    before = engine.engine_stats()["requests_submitted"]
    prs = submit_spans_tiered(engine, spans)
    for (f, off, ln), pr in zip(spans, prs):
        assert bytes(pr.wait()) == payload[off:off + ln]
        assert pr.is_ready()
        pr.release()
    assert engine.engine_stats()["requests_submitted"] == before
    assert engine.stats.cache_hits >= 2
    engine.close(fh)


@pytest.mark.perf
def test_join_pieces_gives_single_view_for_split_extents(tier,
                                                         data_file,
                                                         engine):
    """Consumers that need ONE view per extent (weights row chunks)
    survive the tier's multi-piece hit/miss splits via join_pieces."""
    from nvme_strom_tpu.io.plan import join_pieces
    path, payload = data_file
    fh = engine.open(path)
    fkey = engine.file_key(fh)
    with open(path, "rb") as f:
        raw = f.read()
    tier.fill(fkey, 0, np.frombuffer(raw[:LINE], np.uint8), None)
    a, b = LINE // 2, LINE // 2 + LINE        # straddle: hit + miss
    (pieces,) = plan_and_submit(engine, [(fh, a, b - a)],
                                chunk_bytes=256 << 10)
    assert len(pieces) == 2
    p = join_pieces(pieces, engine.stats)
    assert p.length == b - a and p.offset == a and p.fh == fh
    assert bytes(p.wait()) == payload[a:b]
    p.release()
    # the single-piece case stays the piece itself (zero-copy)
    (pieces,) = plan_and_submit(engine, [(fh, 4 * LINE, LINE)],
                                chunk_bytes=256 << 10)
    assert join_pieces(pieces) is pieces[0]
    for pc in pieces:
        pc.release()
    engine.close(fh)


@pytest.mark.perf
def test_stream_span_unaligned_spans_never_admit(tier, data_file,
                                                 engine):
    """A stream-path span that can never hit (crosses lines / starts
    mid-line) must not fill the tier — no budget squat, no ghost
    churn."""
    path, _payload = data_file
    fh = engine.open(path)
    spans = [(fh, LINE // 2, LINE)]           # crosses a line boundary
    for _ in range(4):
        for pr in submit_spans_tiered(engine, spans):
            pr.wait()
            pr.release()
    assert tier.bytes_resident == 0
    assert engine.stats.cache_admissions == 0
    assert engine.stats.cache_hits == 0
    engine.close(fh)


@pytest.mark.perf
def test_disabled_budget_restores_pre_tier_path(data_file, monkeypatch):
    monkeypatch.setenv("STROM_HOSTCACHE_MB", "0")
    hostcache.reset()
    try:
        assert hostcache.get_cache() is None
        path, payload = data_file
        stats = StromStats()
        eng = StromEngine(_cfg(), stats=stats)
        try:
            fh = eng.open(path)
            for _ in range(3):
                views = plan_and_submit(eng, [(fh, 0, LINE)],
                                        chunk_bytes=256 << 10)
                for pieces in views:
                    for p in pieces:
                        assert bytes(wait_exact(p)) == payload[:LINE]
                        p.release()
            assert stats.cache_hits == 0 and stats.cache_misses == 0
            assert stats.cache_admissions == 0
            eng.close(fh)
        finally:
            eng.close_all()
    finally:
        hostcache.reset()


@pytest.mark.perf
def test_counters_flow_to_strom_stat_json_and_block(tier, data_file,
                                                    tmp_path,
                                                    monkeypatch, capsys):
    """cache_* counters + the bytes-resident gauge ride StromStats →
    the export file → `strom_stat --json` (scripting/dashboards) and
    the rendered "host cache" block."""
    import json as _json

    from nvme_strom_tpu.tools import strom_stat
    export = tmp_path / "stats.json"
    monkeypatch.setenv("STROM_STATS_EXPORT", str(export))
    path, _payload = data_file
    stats = StromStats()
    eng = StromEngine(_cfg(), stats=stats)
    try:
        fh = eng.open(path)
        _warm(tier, eng, fh, [(fh, 0, LINE)], klass="decode")
        _read(eng, [(fh, 0, LINE)], klass="decode")
        eng.close(fh)
    finally:
        eng.close_all()    # sync_stats → export

    rc = strom_stat.main([str(export), "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    snap = _json.loads(out)
    assert snap["cache_hits"] >= 1
    assert snap["cache_admissions"] >= 1
    assert snap["bytes_served_cache"] >= LINE
    assert snap["cache_bytes_resident"] >= LINE
    assert snap["class_stats"]["decode"]["cache_hits"] >= 1

    rc = strom_stat.main([str(export)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "host cache" in out
    assert "hit rate" in out
    assert "class decode" in out


@pytest.mark.perf
def test_watchdog_dump_carries_host_cache_line(tier, data_file):
    import io as _io

    from nvme_strom_tpu.utils.watchdog import StepWatchdog
    path, _payload = data_file
    stats = StromStats()
    eng = StromEngine(_cfg(), stats=stats)
    try:
        fh = eng.open(path)
        _warm(tier, eng, fh, [(fh, 0, LINE)])
        _read(eng, [(fh, 0, LINE)])
        buf = _io.StringIO()
        wd = StepWatchdog(deadline_s=0.05, engine=eng, stream=buf)
        try:
            with wd.step("hc"):
                import time
                time.sleep(0.2)
        finally:
            wd.close()
        dump = buf.getvalue()
        assert "host cache:" in dump
        assert "hits=" in dump and "resident=" in dump
        eng.close(fh)
    finally:
        eng.close_all()


@pytest.mark.perf
def test_record_unit_plans_bypass_the_tier(tier, data_file, engine):
    """split_unit > 1 (fixedrec) keeps the uncached path: line
    boundaries cannot guarantee record-aligned pieces."""
    path, payload = data_file
    fh = engine.open(path)
    for _ in range(3):
        views = plan_and_submit(engine, [(fh, 0, LINE)],
                                chunk_bytes=256 << 10, split_unit=96)
        for pieces in views:
            for p in pieces:
                p.wait()
                p.release()
    assert engine.stats.cache_hits == 0
    assert engine.stats.cache_misses == 0
    engine.close(fh)
