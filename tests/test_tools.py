"""Tests for the L3 CLI utilities (ssd2tpu_test, strom_stat) —
the analogues of the reference's benchmark + stat tools (SURVEY.md §2/§3.4).
"""

import json
import os

import numpy as np
import pytest

from nvme_strom_tpu.tools import ssd2tpu_test, strom_stat


@pytest.fixture
def data_file(tmp_path):
    path = tmp_path / "payload.bin"
    rng = np.random.default_rng(7)
    path.write_bytes(rng.integers(0, 256, 3 * (1 << 20) + 777,
                                  dtype=np.uint8).tobytes())
    return path


def _run(capsys, argv):
    rc = ssd2tpu_test.main(argv)
    out = capsys.readouterr().out.strip().splitlines()[-1]
    return rc, json.loads(out)


def test_ssd2tpu_host_verify(capsys, data_file):
    rc, res = _run(capsys, [str(data_file), "--chunk-bytes", str(1 << 20),
                            "--depth", "3", "--verify"])
    assert rc == 0
    assert res["verify"] == "ok"
    assert res["bytes"] == data_file.stat().st_size
    assert res["gib_per_s"] > 0
    assert res["stats"]["requests_failed"] == 0


def test_ssd2tpu_chunk_byte_exact(capsys, data_file):
    rc, res = _run(capsys, [str(data_file), "--chunk-bytes", str(1 << 20),
                            "--verify-pread", "--depth", "2"])
    assert rc == 0
    assert res["verify"] == "ok"


def test_ssd2tpu_device_dest(capsys, data_file):
    rc, res = _run(capsys, [str(data_file), "--dest", "device",
                            "--chunk-bytes", str(1 << 20), "--verify"])
    assert rc == 0
    assert res["verify"] == "ok"
    assert res["stats"]["bytes_to_device"] >= data_file.stat().st_size


def test_ssd2tpu_total_bytes_cap(capsys, data_file):
    rc, res = _run(capsys, [str(data_file), "--total-bytes", str(1 << 20),
                            "--chunk-bytes", str(256 << 10)])
    assert rc == 0
    assert res["bytes"] == 1 << 20


def test_ssd2tpu_generates_file(capsys, tmp_path):
    rc, res = _run(capsys, ["--make-bytes", str(1 << 20), "--tmpdir",
                            str(tmp_path), "--verify"])
    assert rc == 0
    assert res["verify"] == "ok"
    assert not os.path.exists(res["file"])  # cleaned up without --keep


def test_stats_export_and_strom_stat(capsys, data_file, tmp_path,
                                     monkeypatch):
    export = tmp_path / "strom_stats.json"
    monkeypatch.setenv("STROM_STATS_EXPORT", str(export))

    from nvme_strom_tpu.io.engine import StromEngine
    from nvme_strom_tpu.utils.stats import StromStats

    with StromEngine(stats=StromStats()) as eng:
        fh = eng.open(data_file)
        with eng.submit_read(fh, 0, 4096) as p:
            assert p.wait().nbytes == 4096
        eng.close(fh)
    assert export.exists()

    rc = strom_stat.main([str(export)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "requests_completed" in out

    rc = strom_stat.main([str(export), "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    snap = json.loads(out)
    assert snap["requests_completed"] >= 1
    # North star in the residency-planning regime: every host copy is a
    # PLANNED page-cache read (the data_file fixture is freshly written,
    # hence warm) — unplanned bounce stays zero.
    assert snap["bounce_bytes"] == snap["bytes_resident"]
    assert snap["retries"] == 0


def test_strom_stat_missing_file(capsys, tmp_path, monkeypatch):
    monkeypatch.delenv("STROM_STATS_EXPORT", raising=False)
    assert strom_stat.main([]) == 2
    assert strom_stat.main([str(tmp_path / "absent.json")]) == 2


def test_strom_stat_device_topology(capsys, tmp_path):
    """--device prints the backing blockdev walk (raid members when
    striped) — the observable form of the reference's md-raid0 check."""
    rc = strom_stat.main(["--device", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "device topology" in out
    # Either a real blockdev (with the DMA-eligibility verdict) or an
    # honest no-blockdev report on overlay/tmpfs.
    assert ("direct-DMA eligible" in out
            or "no visible backing blockdev" in out)


def test_transfer_diag_alias_proof(capsys):
    """The zero-copy claim's evidence: a wait() view's data pointer lies
    inside the mlock'd staging pool, 4 KiB-aligned (VERDICT weak #3 —
    instrumentation for the device boundary)."""
    from nvme_strom_tpu.tools import transfer_diag
    res = transfer_diag.run(1 << 20, repeats=2)
    assert res["view_in_pool"] is True
    assert res["view_aligned"] is True
    assert res["verdict"] == "zero-copy to PJRT boundary"
    assert res["t_staging_s"] > 0 and res["t_copy_heap_s"] > 0


def test_strom_stat_renders_member_bytes(capsys):
    """Per-member attribution shows up in the CLI render with shares."""
    from nvme_strom_tpu.tools.strom_stat import render
    out = render({"bytes_direct": 4096, "bounce_bytes": 0,
                  "member_bytes": {"nvme0n1": 3 << 20, "nvme1n1": 1 << 20}})
    assert "per-member payload" in out
    assert "nvme0n1" in out and "75.0%" in out
    assert "nvme1n1" in out and "25.0%" in out


def test_strom_stat_renders_kv_serving_block():
    """The serving prefix-store counters get their own block: hit
    rate, dedupe savings, restore p99 — and stay invisible on a run
    with no store traffic."""
    from nvme_strom_tpu.tools.strom_stat import render
    out = render({"bytes_direct": 4096, "bounce_bytes": 0,
                  "kv_prefix_hits": 30, "kv_prefix_misses": 10,
                  "kv_pages_deduped": 12, "kv_bytes_saved": 3 << 20,
                  "kv_pages_written": 4, "kv_pages_restored": 30,
                  "kv_store_pages_resident": 4,
                  "kv_restore_p99_ms": 12.5})
    assert "kv serving" in out
    assert "kv_pages_deduped" in out and "12" in out
    assert "3.00 MiB" in out                  # kv_bytes_saved humanized
    assert "0.750" in out                     # prefix hit rate
    assert "12.50 ms" in out                  # restore p99
    quiet = render({"bytes_direct": 4096, "bounce_bytes": 0})
    assert "kv serving" not in quiet


def test_strom_stat_json_carries_kv_counters(capsys, tmp_path,
                                             monkeypatch):
    """--json round-trips the kv_* counters an exporting engine
    wrote (the fleet-tooling contract of the satellite)."""
    import json as _json
    from nvme_strom_tpu.utils.stats import StromStats
    export = tmp_path / "stats.json"
    monkeypatch.setenv("STROM_STATS_EXPORT", str(export))
    st = StromStats()
    st.add(kv_prefix_hits=5, kv_pages_deduped=2, kv_bytes_saved=1024)
    st.set_gauges(kv_restore_p99_ms=7.25)
    st.maybe_export()
    rc = strom_stat.main([str(export), "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    snap = _json.loads(out)
    assert snap["kv_prefix_hits"] == 5
    assert snap["kv_pages_deduped"] == 2
    assert snap["kv_restore_p99_ms"] == 7.25


def test_watchdog_dump_carries_kv_serving_line():
    """A watchdog timeout dump includes the kv-serving line when the
    store saw traffic (and omits it otherwise)."""
    import io as _io
    import time as _time
    from nvme_strom_tpu.utils.stats import StromStats
    from nvme_strom_tpu.utils.watchdog import StepWatchdog

    class Eng:
        def __init__(self, stats):
            self.stats = stats

        def sync_stats(self):
            return {}

    for traffic, expect in ((True, True), (False, False)):
        st = StromStats()
        if traffic:
            st.add(kv_prefix_hits=3, kv_pages_restored=3,
                   kv_pages_written=2)
        stream = _io.StringIO()
        wd = StepWatchdog(deadline_s=0.05, engine=Eng(st),
                          stream=stream, max_reports=1)
        with wd.step("kv"):
            _time.sleep(0.2)
        wd.close()
        dump = stream.getvalue()
        assert "watchdog" in dump
        assert ("kv serving:" in dump) is expect, dump


def test_profile_classify_first_match_wins():
    """A matmul fusion must land in the matmul bucket even though its
    name also says "fusion" — the bucket order IS the precedence."""
    from nvme_strom_tpu.tools.profile_report import classify
    assert classify("%convolution_reduce_fusion = f32[] fusion(...)") \
        == "matmul"
    assert classify("%dot.54") == "matmul"
    assert classify("%tpu_custom_call.3") == "attention-kernel"
    assert classify("%copy-start.1") == "copy"
    assert classify("%add_multiply_fusion.2") == "elementwise-fusion"
    # a bare fusion name carries no constituent evidence: its own
    # bucket, never a claim of elementwise (nor matmul) work
    assert classify("%fusion.212") == "unnamed-fusion"
    assert classify("%while.7") == "other"


def test_profile_classify_ignores_operands():
    """Classification must come from the op's own identity, never its
    operand list — the 2026-07-31 window ledgered '69% copy' because a
    matmul fusion consuming %transpose operands keyword-matched copy."""
    from nvme_strom_tpu.tools.profile_report import classify, event_bucket
    # full HLO line: dot op with a transposed operand — matmul, not copy
    assert classify("%f.1 = bf16[8,16]{1,0} dot(%transpose.5, %p.2), "
                    "lhs_contracting_dims={1}") == "matmul"
    # explicit copy op with a dot-named operand — copy, not matmul
    assert classify("%copy.9 = bf16[8]{0} copy(%dot.3)") == "copy"
    # bare fusion: falls back to the lhs name's constituents
    assert classify("%multiply_reduce_fusion.38 = f32[] fusion("
                    "%custom-call.2), kind=kOutput") == "reduce"

    class Ev:          # xprof's own category stat wins when present
        name = "%fusion.212 = bf16[] fusion(%transpose.1)"
        stats = [("hlo_category", "convolution fusion")]
    assert event_bucket(Ev()) == "matmul"

    class Ev2:         # no stat → name path
        name = "%fusion.7 = bf16[] fusion(%p)"
        stats = []
    assert event_bucket(Ev2()) == "unnamed-fusion"


def test_profile_fusion_map_resolves_buckets(tmp_path):
    """The dumped post-optimization HLO resolves bare %fusion.NN events
    to their constituent opcodes: a dot-containing output fusion is MXU
    work, a reduce-calling loop fusion is reduction work — the exact
    attribution the bare name ('unnamed-fusion', ~70% of device time in
    the valid window-7 parses) cannot provide."""
    hlo = """HloModule jit_train_step

%fused_computation.1 (p0: bf16[8,128]) -> bf16[8,128] {
  %p0 = bf16[8,128]{1,0} parameter(0)
  %p1 = bf16[128,128]{1,0} parameter(1)
  ROOT %dot.3 = bf16[8,128]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}
}

%fused_computation.2 (p0: f32[8,128]) -> f32[8] {
  %p0 = f32[8,128]{1,0} parameter(0)
  %c = f32[] constant(0)
  ROOT %reduce.1 = f32[8]{0} reduce(%p0, %c), dimensions={1}
}

ENTRY %main.9 (a: bf16[8,128]) -> f32[8] {
  %fusion.10 = bf16[8,128]{1,0} fusion(%a), kind=kOutput, calls=%fused_computation.1
  ROOT %fusion.11 = f32[8]{0} fusion(%fusion.10), kind=kLoop, calls=%fused_computation.2
}
"""
    (tmp_path / "optimized_hlo.txt").write_text(hlo)
    from nvme_strom_tpu.tools import profile_report
    fmap = profile_report.load_fusion_map(str(tmp_path))
    # sigil-less keys: TPU device planes log "%fusion.NN", CPU host
    # planes "fusion.NN" — the map matches both
    assert fmap["fusion.10"] == "matmul-fusion"
    assert fmap["fusion.11"] == "reduce-fusion"

    class Ev:    # resolved map beats both the stat and the bare name
        name = "%fusion.10 = bf16[8,128]{1,0} fusion(%a), kind=kOutput"
        stats = [("hlo_category", "loop fusion")]
    assert profile_report.event_bucket(Ev(), fmap) == "matmul-fusion"
    # no map → empty dict → unchanged fallback behavior
    assert profile_report.load_fusion_map("/nonexistent-dir") == {}

    # MXU-efficiency half: the dot inside %fused_computation.1 is
    # (8,128)@(128,128) → 2·(8·128)·128 FLOPs, attributed to the
    # calling %fusion.10; the reduce-only fusion gets no entry
    flops = profile_report.load_fusion_flops(str(tmp_path))
    assert flops["fusion.10"] == 2 * (8 * 128) * 128
    assert "fusion.11" not in flops
    assert profile_report.load_fusion_flops("/nonexistent-dir") == {}


def test_profile_matmul_flops_batched_conv_and_malformed():
    """2·|out|·K is exact for batched dots (batch dims ride the output
    product) and for XLA's matmul-as-convolution spelling; malformed
    lines read as 0, never a wrong estimate."""
    from nvme_strom_tpu.tools import profile_report
    line = ("%dot.7 = bf16[4,256,512]{2,1,0:T(8,128)(2,1)} "
            "dot(bf16[4,256,64]{2,1,0} %a, bf16[4,64,512]{2,1,0} %b), "
            "lhs_batch_dims={0}, lhs_contracting_dims={2}, "
            "rhs_batch_dims={0}, rhs_contracting_dims={1}")
    assert (profile_report._matmul_flops(line, "dot", {})
            == 2 * (4 * 256 * 512) * 64)
    # optimized modules spell dW = x^T @ dy as a convolution with
    # dim_labels=fb_io->bf: K = lhs 'f' dim (the contracted batch)
    conv = ("ROOT %convolution.5 = bf16[256,512]{1,0:T(8,128)(2,1)} "
            "convolution(%a, %b), dim_labels=fb_io->bf")
    defs = {"a": [128, 256], "b": [128, 512]}
    assert (profile_report._matmul_flops(conv, "convolution", defs)
            == 2 * (256 * 512) * 128)
    assert profile_report._matmul_flops("%dot.8 = garbage", "dot", {}) == 0


def test_profile_hlo_param_names_scoped_per_computation(tmp_path):
    """Computation-header/parameter names (p0, param_0) repeat across
    fused computations; a module-wide defs map let a LATER computation's
    same-named param overwrite an earlier one and mis-size K for
    operands without inline shapes (round-4 advisor).  Here two fusions
    both name their param %p0 with different K dims — each dot must be
    sized by ITS OWN computation's p0."""
    hlo = """HloModule jit_scoped

%fused_computation.1 (p0: bf16[8,64]) -> bf16[8,32] {
  %p0 = bf16[8,64]{1,0} parameter(0)
  %w1 = bf16[64,32]{1,0} parameter(1)
  ROOT %dot.1 = bf16[8,32]{1,0} dot(%p0, %w1), lhs_contracting_dims={1}
}

%fused_computation.2 (p0: bf16[8,4096]) -> bf16[8,32] {
  %p0 = bf16[8,4096]{1,0} parameter(0)
  %w2 = bf16[4096,32]{1,0} parameter(1)
  ROOT %dot.2 = bf16[8,32]{1,0} dot(%p0, %w2), lhs_contracting_dims={1}
}

ENTRY %main.9 (a: bf16[8,64], b: bf16[8,4096]) -> bf16[8,32] {
  %fusion.1 = bf16[8,32]{1,0} fusion(%a), kind=kOutput, calls=%fused_computation.1
  ROOT %fusion.2 = bf16[8,32]{1,0} fusion(%b), kind=kOutput, calls=%fused_computation.2
}
"""
    (tmp_path / "optimized_hlo.txt").write_text(hlo)
    from nvme_strom_tpu.tools import profile_report
    flops = profile_report.load_fusion_flops(str(tmp_path))
    # fusion.1's dot contracts K=64, fusion.2's K=4096 — the flat-map
    # bug sized BOTH by the last-seen p0 (K=4096)
    assert flops["fusion.1"] == 2 * (8 * 32) * 64
    assert flops["fusion.2"] == 2 * (8 * 32) * 4096


def test_profile_report_capture_and_parse(capsys, monkeypatch):
    """End-to-end on the CPU backend: trace a tiny train variant, parse
    the xplane protobuf, and emit the one-line breakdown the watcher
    ledgers (verdict #3's profile-attribution evidence path)."""
    monkeypatch.setenv("STROM_SUITE_TINY_COMPUTE", "1")
    from nvme_strom_tpu.tools import profile_report
    rc = profile_report.main(["--batch", "2", "--seq", "64"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["metric"] == "config7:profile-breakdown"
    assert rec["device_busy_ms"] > 0
    assert rec["tflops"] > 0
    fracs = rec["category_frac"]
    assert abs(sum(fracs.values()) - 1.0) < 1e-3
    assert rec["top_ops_ms"]          # non-empty attribution
    assert "matmul" in rec["category_ms"] or "other" in rec["category_ms"]
    # the capture step dumps the optimized HLO next to the trace, so
    # the parse resolves fusion constituents (0 only if the dump was
    # unavailable, which the CPU backend always serves) — and the
    # resolution must have APPLIED to traced time, not just loaded
    assert rec["fusions_resolved"] > 0
    assert rec["fusion_resolved_ms"] > 0


def test_profile_report_missing_dir():
    """--dir on an empty directory fails loudly, not with a zero row."""
    from nvme_strom_tpu.tools import profile_report
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        with pytest.raises(FileNotFoundError):
            profile_report.parse_trace(d)
