"""Tracing + latency observability (SURVEY.md §5 "Tracing/profiling"):
per-request spans exported as chrome://tracing JSON, log2 latency
histograms with percentile summaries — the upgrade over the reference's
aggregate-only STAT_INFO counters."""

import json

import pytest

from nvme_strom_tpu.io import StromEngine
from nvme_strom_tpu.utils.config import EngineConfig
from nvme_strom_tpu.utils.stats import StromStats, percentiles_from_log2_hist
from nvme_strom_tpu.utils.trace import Tracer


def _engine(tracer=None):
    cfg = EngineConfig(chunk_bytes=1 << 20, queue_depth=4,
                       buffer_pool_bytes=8 << 20)
    return StromEngine(cfg, stats=StromStats(), tracer=tracer)


def test_read_spans_recorded(tmp_data_file, tmp_path):
    path, payload = tmp_data_file
    out = tmp_path / "trace.json"
    tracer = Tracer(str(out))
    with _engine(tracer) as eng:
        fh = eng.open(path)
        for off in range(0, len(payload), 1 << 20):
            n = min(1 << 20, len(payload) - off)
            with eng.submit_read(fh, off, n) as p:
                p.wait()
        eng.close(fh)
    n_chunks = (len(payload) + (1 << 20) - 1) // (1 << 20)
    assert len(tracer) == n_chunks
    assert tracer.export() == str(out)
    doc = json.loads(out.read_text())
    evs = doc["traceEvents"]
    assert len(evs) == n_chunks
    for ev in evs:
        assert ev["ph"] == "X"
        assert ev["name"].startswith("strom.read")
        assert ev["dur"] >= 0
        assert ev["args"]["bytes"] > 0
    # spans are ordered and timestamped on the same clock
    assert all(e["ts"] > 0 for e in evs)


def test_write_spans_recorded(tmp_path):
    import numpy as np
    tracer = Tracer(str(tmp_path / "t.json"))
    with _engine(tracer) as eng:
        fh = eng.open(tmp_path / "out.bin", writable=True)
        eng.submit_write(fh, 0, np.zeros(4096, np.uint8)).wait()
        eng.close(fh)
    assert len(tracer) == 1


def test_disabled_tracer_records_nothing(tmp_data_file):
    path, payload = tmp_data_file
    tracer = Tracer()  # no path -> disabled
    with _engine(tracer) as eng:
        fh = eng.open(path)
        with eng.submit_read(fh, 0, 4096) as p:
            p.wait()
        eng.close(fh)
    assert len(tracer) == 0
    assert tracer.export() is None


def test_span_context_manager(tmp_path):
    tracer = Tracer(str(tmp_path / "t.json"))
    with tracer.span("unit.work", items=3):
        pass
    assert len(tracer) == 1
    tracer.export()
    ev = json.loads((tmp_path / "t.json").read_text())["traceEvents"][0]
    assert ev["name"] == "unit.work" and ev["args"]["items"] == 3


def test_latency_histogram_counts_requests(tmp_data_file):
    path, payload = tmp_data_file
    with _engine() as eng:
        fh = eng.open(path)
        n_reqs = 8
        for _ in range(n_reqs):
            with eng.submit_read(fh, 0, 4096) as p:
                p.wait()
        hist = eng.latency_histogram()
        assert sum(hist["read"]) == n_reqs
        assert sum(hist["write"]) == 0
        pct = eng.latency_percentiles("read")
        assert pct[50] > 0 and pct[99] >= pct[50]
        eng.close(fh)


def test_latency_gauges_exported(tmp_data_file, tmp_path, monkeypatch):
    path, _ = tmp_data_file
    export = tmp_path / "stats.json"
    monkeypatch.setenv("STROM_STATS_EXPORT", str(export))
    with _engine() as eng:
        fh = eng.open(path)
        with eng.submit_read(fh, 0, 4096) as p:
            p.wait()
        eng.close(fh)
    snap = json.loads(export.read_text())
    assert snap["lat_read_p50_us"] > 0
    assert snap["lat_read_p99_us"] >= snap["lat_read_p50_us"]


_GM = 2 ** 0.5   # per-bucket geometric mean factor (utils/stats.py)


@pytest.mark.parametrize("hist,expect", [
    ([0] * 64, {50: 0, 90: 0, 99: 0}),
    ([0, 0, 4], {50: int(4 * _GM), 90: int(4 * _GM), 99: int(4 * _GM)}),
])
def test_percentiles_from_log2_hist(hist, expect):
    assert percentiles_from_log2_hist(hist, ps=(50, 90, 99)) == expect


def test_percentiles_spread():
    hist = [0] * 64
    hist[10] = 90   # 90 fast requests ~1µs
    hist[20] = 10   # 10 slow ~1ms
    pct = percentiles_from_log2_hist(hist, ps=(50, 99))
    assert pct[50] == int(2 ** 10 * _GM)
    assert pct[99] == int(2 ** 20 * _GM)
