"""examples/train_lm.py end-to-end: train, checkpoint, resume (CPU)."""

import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _run(tmp_path, steps, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    r = subprocess.run(
        [sys.executable, str(REPO / "examples" / "train_lm.py"),
         "--tiny", "--steps", str(steps), "--save-every", "2",
         "--global-batch", "4", "--tp", "2",
         "--ckpt-dir", str(tmp_path / "ckpt"),
         "--data-dir", str(tmp_path / "data"), *extra],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(REPO))
    assert r.returncode == 0, r.stderr[-2000:]
    return r.stdout


def test_train_checkpoint_resume(tmp_path):
    (tmp_path / "data").mkdir()
    # synthesize data once via the script's own helper
    sys.path.insert(0, str(REPO))
    from examples.train_lm import _synthesize_shards
    from nvme_strom_tpu.models.transformer import tiny_config
    _synthesize_shards(str(tmp_path / "data"), tiny_config(),
                       n_shards=2, per_shard=8)

    # warmup+cosine schedule and grad clipping ride the same run — the
    # optimizer count inside the checkpoint keeps the schedule position
    # coherent across the resume
    sched = ("--lr-schedule", "cosine", "--warmup-steps", "2",
             "--grad-clip", "1.0")
    out1 = _run(tmp_path, steps=4, extra=sched)
    assert "step 4" in out1
    assert (tmp_path / "ckpt").is_dir()

    out2 = _run(tmp_path, steps=6, extra=sched)   # resumes from step 4
    assert "resumed from step 4" in out2
    assert "step 6" in out2
    losses = [float(m) for m in re.findall(r"loss=([\d.]+)", out1 + out2)]
    assert losses and all(l == l and l < 100 for l in losses)  # finite


def test_train_lora_checkpoint_resume(tmp_path):
    """--lora trains adapters only, checkpoints them, and resumes."""
    (tmp_path / "data").mkdir()
    sys.path.insert(0, str(REPO))
    from examples.train_lm import _synthesize_shards
    from nvme_strom_tpu.models.transformer import tiny_config
    _synthesize_shards(str(tmp_path / "data"), tiny_config(),
                       n_shards=2, per_shard=8)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")

    def run(steps):
        r = subprocess.run(
            [sys.executable, str(REPO / "examples" / "train_lm.py"),
             "--tiny", "--steps", str(steps), "--save-every", "2",
             "--global-batch", "4", "--tp", "2", "--lora", "4",
             "--ckpt-dir", str(tmp_path / "ckpt"),
             "--data-dir", str(tmp_path / "data")],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=str(REPO))
        assert r.returncode == 0, r.stderr[-2000:]
        return r.stdout

    out1 = run(4)
    assert "lora: rank 4" in out1
    m = re.search(r"(\d+) trainable of (\d+) base", out1)
    assert m and int(m.group(1)) < int(m.group(2)) // 5
    out2 = run(6)
    assert "resumed from step 4" in out2
    assert "step 6" in out2


def test_train_offloaded_optimizer_resume(tmp_path):
    """--offload-opt: Adam moments live on NVMe; training runs, loss is
    finite, and a second invocation resumes the moment manifest."""
    (tmp_path / "data").mkdir()
    sys.path.insert(0, str(REPO))
    from examples.train_lm import _synthesize_shards
    from nvme_strom_tpu.models.transformer import tiny_config
    _synthesize_shards(str(tmp_path / "data"), tiny_config(),
                       n_shards=2, per_shard=8)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")

    def run(steps):
        r = subprocess.run(
            [sys.executable, str(REPO / "examples" / "train_lm.py"),
             "--tiny", "--steps", str(steps), "--save-every", "2",
             "--global-batch", "4", "--tp", "2",
             "--offload-opt", str(tmp_path / "opt"),
             "--ckpt-dir", str(tmp_path / "ckpt"),
             "--data-dir", str(tmp_path / "data")],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=str(REPO))
        assert r.returncode == 0, r.stderr[-2000:]
        return r.stdout

    out1 = run(4)
    assert "offload-opt:" in out1 and "resumed at step 0" in out1
    assert (tmp_path / "opt" / "moments.bin").exists()
    losses = [float(m) for m in re.findall(r"loss=([\d.]+)", out1)]
    assert losses and all(l == l and l < 100 for l in losses)
    out2 = run(6)
    assert "resumed from step 4" in out2
    assert "resumed at step 4" in out2   # the moment manifest, separately
    assert "step 6" in out2

    # crash-window refusal: a moment manifest ahead of the params
    # checkpoint must refuse to pair (silent Adam divergence otherwise)
    import json
    mpath = tmp_path / "opt" / "moments.json"
    m = json.loads(mpath.read_text())
    m["step"] = 99
    mpath.write_text(json.dumps(m))
    r = subprocess.run(
        [sys.executable, str(REPO / "examples" / "train_lm.py"),
         "--tiny", "--steps", "8", "--save-every", "2",
         "--global-batch", "4", "--tp", "2",
         "--offload-opt", str(tmp_path / "opt"),
         "--ckpt-dir", str(tmp_path / "ckpt"),
         "--data-dir", str(tmp_path / "data")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(REPO))
    assert r.returncode != 0
    assert "divergent trajectory" in r.stderr


def test_train_full_offload_triad(tmp_path):
    """--offload-opt + --remat nvme --offload-acts in ONE run: weights
    stream in at init, Adam moments live on NVMe, AND layer activations
    round-trip through the engine per step — the full
    larger-than-device-memory story in a single command.  Single
    device (the activation store's ordered io_callbacks are
    single-device by scope)."""
    (tmp_path / "data").mkdir()
    sys.path.insert(0, str(REPO))
    from examples.train_lm import _synthesize_shards
    from nvme_strom_tpu.models.transformer import tiny_config
    _synthesize_shards(str(tmp_path / "data"), tiny_config(),
                       n_shards=2, per_shard=8)
    # the pytest conftest exports an 8-device XLA_FLAGS — override it:
    # this path is single-device by design and guards against meshes
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    r = subprocess.run(
        [sys.executable, str(REPO / "examples" / "train_lm.py"),
         "--tiny", "--steps", "3", "--save-every", "2",
         "--global-batch", "4",
         "--offload-opt", str(tmp_path / "opt"),
         "--remat", "nvme", "--offload-acts", str(tmp_path / "acts"),
         "--ckpt-dir", str(tmp_path / "ckpt"),
         "--data-dir", str(tmp_path / "data")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(REPO))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "offload-opt:" in r.stdout
    assert "offload-acts:" in r.stdout
    assert (tmp_path / "acts" / "acts.bin").exists()
    losses = [float(m) for m in re.findall(r"loss=([\d.]+)", r.stdout)]
    assert losses and all(l == l and l < 100 for l in losses)


def test_train_vit_fixedrec(tmp_path):
    """examples/train_vit.py: the config-3 consumer loop — fixedrec
    records stream to device and decode THERE (slice + bitcast inside
    the jitted step)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    r = subprocess.run(
        [sys.executable, str(REPO / "examples" / "train_vit.py"),
         "--steps", "4", "--global-batch", "8", "--tp", "2",
         "--image-size", "32", "--classes", "10"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(REPO))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "step 4" in r.stdout
    losses = [float(m) for m in re.findall(r"loss=([\d.]+)", r.stdout)]
    assert losses and all(l == l and l < 100 for l in losses)
    assert "engine stats" in r.stdout


def test_eval_ppl_cli(tmp_path):
    """examples/eval_ppl.py: npy tokens → finite perplexity ~vocab for
    an untrained model on uniform-random tokens."""
    import json
    import numpy as np
    sys.path.insert(0, str(REPO))
    from nvme_strom_tpu.models.transformer import init_params, tiny_config
    from nvme_strom_tpu.parallel.weights import save_checkpoint
    import jax
    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    wdir = tmp_path / "w"
    wdir.mkdir()
    save_checkpoint(str(wdir / "model.safetensors"), params)
    with open(wdir / "strom_config.json", "w") as f:
        json.dump({k: v for k, v in cfg.__dict__.items()
                   if k != "dtype"}, f)
    rng = np.random.default_rng(0)
    np.save(tmp_path / "ev.npy",
            rng.integers(0, cfg.vocab, (12, 32)).astype(np.int32))
    r = subprocess.run(
        [sys.executable, str(REPO / "examples" / "eval_ppl.py"),
         "--weights", str(wdir), "--npy", str(tmp_path / "ev.npy"),
         "--batch", "4"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=str(REPO))
    assert r.returncode == 0, r.stderr[-2000:]
    ppl = float(r.stdout.split("perplexity:")[1].split()[0])
    # untrained model ≈ uniform over vocab
    assert 0.5 * cfg.vocab < ppl < 4 * cfg.vocab
    # the mixed quant recipe (--int8 --int4: int8 lm_head, int4 rest)
    # runs the same eval and stays in the uniform band
    r = subprocess.run(
        [sys.executable, str(REPO / "examples" / "eval_ppl.py"),
         "--weights", str(wdir), "--npy", str(tmp_path / "ev.npy"),
         "--batch", "4", "--int8", "--int4"],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=str(REPO))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "int4: matmul weights packed" in r.stdout
    ppl4 = float(r.stdout.split("perplexity:")[1].split()[0])
    assert 0.5 * cfg.vocab < ppl4 < 4 * cfg.vocab


def test_sql_query_example_runs():
    """The Direct-SQL demo CLI end to end (synthesized table, range
    predicate, string GROUP BY + top-k)."""
    import subprocess
    import sys
    from pathlib import Path
    repo = Path(__file__).resolve().parents[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, str(repo / "examples" / "sql_query.py"),
         "--rows", "50000", "--where", "w", "100", "5000"],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=str(repo))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "GROUP BY k" in r.stdout
    assert "top-3 by count" in r.stdout
