/* strom_io.h — C ABI of the strom-io engine.
 *
 * This header is the TPU build's analogue of the reference's nvme_strom.h
 * ioctl ABI (SURVEY.md §1 L2): the stable contract between the native I/O
 * engine and all userspace consumers (the ctypes wrapper in
 * nvme_strom_tpu/io/).  Correspondence:
 *
 *   STROM_IOCTL__CHECK_FILE        -> strom_check_file()
 *   STROM_IOCTL__MAP_GPU_MEMORY    -> engine-owned locked buffer pool
 *                                     (created once in strom_engine_create)
 *   STROM_IOCTL__MEMCPY_SSD2GPU    -> strom_submit_read()
 *   STROM_IOCTL__MEMCPY_SSD2GPU_WAIT -> strom_wait()
 *   STROM_IOCTL__STAT_INFO         -> strom_get_stats()
 *
 * All functions return 0 / a non-negative id on success and a negative errno
 * on failure, mirroring the ioctl convention.
 */
#ifndef STROM_IO_H
#define STROM_IO_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct strom_engine strom_engine;

/* Result of strom_check_file — the CHECK_FILE eligibility probe
 * (SURVEY.md §3.3).  Instead of "is ext4/xfs on NVMe", the TPU-relevant
 * questions are: does the fs accept O_DIRECT (page-cache bypass possible)
 * and what alignment does it demand. */
typedef struct strom_file_info {
  int64_t  size;           /* file size in bytes */
  int32_t  supports_direct;/* 1 if O_DIRECT open+read works here */
  int32_t  block_size;     /* required O_DIRECT alignment (logical block) */
  uint64_t fs_magic;       /* statfs f_type */
} strom_file_info;

typedef struct strom_stats_blk {
  uint64_t bytes_direct;         /* payload read via O_DIRECT (no host copy) */
  uint64_t bytes_fallback;       /* payload via buffered fallback            */
  uint64_t bounce_bytes;         /* bytes memcpy'd host-side after landing   */
  uint64_t bytes_written_direct; /* write path (checkpointing)               */
  uint64_t requests_submitted;
  uint64_t requests_completed;
  uint64_t requests_failed;
  uint64_t retries;
  uint64_t bytes_resident;       /* planned page-cache reads: the submit-time
                                    mincore probe found the span resident and
                                    CHOSE buffered (the reference's proactive
                                    resident-block return, SURVEY.md §3.1) —
                                    a subset of bytes_fallback, and NOT a
                                    rescue (retries unaffected)              */
  uint64_t submit_batches;       /* strom_submit_readv calls (n >= 1)        */
  uint64_t submit_syscalls_saved;/* INLINE-dispatched extents per batch
                                    beyond the first: submission round trips
                                    a per-extent caller would have paid
                                    (io_uring_enter doorbells on the uring
                                    backend).  Extents that defer on pool
                                    pressure ring their own doorbell later
                                    and are never credited.  With SQPOLL
                                    active this ALSO counts every doorbell
                                    the poller made unnecessary (an
                                    io_uring_enter the submitter skipped
                                    because the SQ thread was awake; the
                                    worker-pool backend counts elided
                                    dispatch wakeups the same way).         */
  uint64_t submit_enters;        /* submission doorbells actually rung:
                                    io_uring_enter submit/wakeup calls on
                                    the uring backend, dispatch wakeups on
                                    the worker pool.  enters/GiB is the
                                    steady-state submission-syscall rate
                                    bench.py's overlap scenario prices;
                                    SQPOLL drives it toward zero.           */
} strom_stats_blk;

typedef struct strom_completion {
  const uint8_t *data;   /* pointer into an engine buffer; valid until
                            strom_release(req_id). Payload starts here
                            (alignment head already skipped).            */
  uint64_t len;          /* payload length actually read                 */
  int32_t  status;       /* 0 ok; negative errno                         */
  int32_t  was_fallback; /* 1 if this request took the buffered path     */
  uint64_t submit_ns;    /* CLOCK_MONOTONIC at submit                    */
  uint64_t complete_ns;  /* CLOCK_MONOTONIC at completion                */
} strom_completion;

/* Per-request latency histograms (submit->complete), log2-ns buckets:
 * bucket i counts SUCCESSFUL requests with latency in [2^i, 2^(i+1)) ns
 * (failed requests are excluded; see requests_failed).  The
 * reference exposes only aggregate byte/request counters via STAT_INFO
 * (SURVEY.md §5 Tracing: "minimal") — this is the promised upgrade. */
#define STROM_LAT_BUCKETS 64
void strom_get_latency(strom_engine *eng,
                       uint64_t out_read[STROM_LAT_BUCKETS],
                       uint64_t out_write[STROM_LAT_BUCKETS]);

/* Create an engine.
 *   queue_depth  — io_uring SQ depth / worker count for the fallback pool
 *   n_buffers    — buffers in the staging pool (>= queue_depth recommended)
 *   buf_bytes    — payload capacity of each buffer (max read size)
 *   alignment    — O_DIRECT alignment (power of two, >= 512)
 *   use_io_uring — 0 forces the thread-pool backend
 *   lock_buffers — mlock the pool (pin pages, as MAP_GPU_MEMORY pins BAR1)
 * Returns NULL on failure (errno set).
 *
 * Fault injection below the C ABI (chaos/stress runs; default off) is
 * read from the environment at create time:
 *   STROM_FAULT_READ_EIO_EVERY=N    every Nth read completes -EIO
 *   STROM_FAULT_READ_SHORT_EVERY=N  every Nth read reports half its bytes
 *   STROM_FAULT_READ_DELAY_MS=D     every read completion held D ms
 *   STROM_FAULT_WRITE_EIO_EVERY=N   every Nth write completes -EIO
 *   STROM_FAULT_WRITE_ENOSPC_EVERY=N  every Nth write completes -ENOSPC
 *   STROM_FAULT_WRITE_SHORT_EVERY=N every Nth write reports half its bytes
 *   STROM_FAULT_WRITE_DELAY_MS=D    every write completion held D ms
 *   STROM_FAULT_RING_STALL_RING=R   arm ring R's stall injection (see
 *                                   strom_set_ring_stall): its requests
 *                                   park instead of dispatching
 *   STROM_FAULT_RING_STALL_AFTER=N  first N dispatches run clean before
 *                                   the stall engages (default 0)
 * The Python-level plan (nvme_strom_tpu/io/faults.py) is richer and
 * deterministic; these knobs exist to exercise the native completion
 * path itself.
 *
 * Zero-copy submission knobs (PR 12; also read at create time):
 *   STROM_REG_FILES=0     disable the registered-file slot table
 *                         (default on; soft-fails on kernels without
 *                         sparse IORING_REGISTER_FILES support)
 *   STROM_SQPOLL=1        enable SQPOLL: the uring backend sets
 *                         IORING_SETUP_SQPOLL so a kernel thread
 *                         consumes SQEs without io_uring_enter; the
 *                         worker-pool backend runs the same state
 *                         machine with polling workers (a dispatch
 *                         whose poller is awake skips the wakeup).
 *                         Default off: the poller burns a core.
 *   STROM_SQPOLL_IDLE_MS  poller idle budget before it sleeps and
 *                         submissions need a wakeup doorbell again
 *                         (default 50)
 */
strom_engine *strom_engine_create(uint32_t queue_depth, uint32_t n_buffers,
                                  uint64_t buf_bytes, uint32_t alignment,
                                  int use_io_uring, int lock_buffers);

/* Multi-ring engine: N independent submission rings (io_uring instance
 * or worker pool EACH, with private completion reaping and a private
 * request table) behind ONE file table, ONE public ABI, and ONE
 * fungible staging pool (global pool + global deferral FIFO: a batch
 * pinned to one ring can never deadlock behind a per-ring buffer slice
 * smaller than a consumer's in-flight window — buffers freed on any
 * ring hand over to the oldest deferred request engine-wide).  The
 * single-ring engine serializes every consumer through one doorbell;
 * sharding lets concurrent traffic classes (decode-critical reads vs
 * bulk prefetch vs scrub) ride disjoint queues — the QoS scheduler
 * above (io/sched.py) decides which class lands on which ring.
 * queue_depth and n_buffers are PER RING.  strom_engine_create(...) ==
 * strom_engine_create_rings(1, ...), bit-for-bit the old behavior.
 * Request ids encode their ring in the low STROM_RING_ID_BITS bits, so
 * wait/release route lock-free. */
#define STROM_MAX_RINGS 64
#define STROM_RING_ID_BITS 6
strom_engine *strom_engine_create_rings(uint32_t n_rings,
                                        uint32_t queue_depth,
                                        uint32_t n_buffers,
                                        uint64_t buf_bytes,
                                        uint32_t alignment,
                                        int use_io_uring, int lock_buffers);
void strom_engine_destroy(strom_engine *eng);

/* ---- unified pinned arena (io/arena.py, PR 12) ----------------------
 * ONE anonymous reservation (MAP_NORESERVE: virtual until touched) the
 * Python allocator carves into engine staging slices, host-cache lines
 * and bridge DMA slabs — one mmap, one mlock policy, zero copies
 * between pinned regions.  strom_arena_lock pins one carve (best
 * effort: returns 0 or -errno; RLIMIT_MEMLOCK refusal is not fatal). */
void *strom_arena_create(uint64_t bytes);
void strom_arena_destroy(void *base, uint64_t bytes);
int strom_arena_lock(void *base, uint64_t bytes);

/* Exact staging-pool footprint strom_engine_create_rings would map for
 * this geometry (buf_cap slack included) — what the arena carve for a
 * preallocated engine must provide.  0 on invalid geometry. */
uint64_t strom_engine_pool_bytes(uint32_t n_rings, uint32_t n_buffers,
                                 uint64_t buf_bytes, uint32_t alignment);

/* strom_engine_create_rings over a CALLER-OWNED staging pool (an arena
 * carve): the engine stages/DMA-targets/registers `pool` exactly as it
 * would its own mapping but never munmaps it — the arena outlives the
 * engine.  `pool_bytes` must be >= strom_engine_pool_bytes(...) and
 * `pool` alignment-conformant (the arena carves page-aligned).  NULL +
 * errno on failure, like strom_engine_create. */
strom_engine *strom_engine_create_prealloc(uint32_t n_rings,
                                           uint32_t queue_depth,
                                           uint32_t n_buffers,
                                           uint64_t buf_bytes,
                                           uint32_t alignment,
                                           int use_io_uring,
                                           int lock_buffers,
                                           void *pool,
                                           uint64_t pool_bytes);

/* Per-ring introspection: the scheduler's dispatch decisions key off
 * in-flight queue depth (submitted - completed, lock-free atomics — the
 * poll can run at dispatch frequency without touching the ring mutex);
 * free_buffers/deferred take the ring lock briefly. */
typedef struct strom_ring_info {
  uint32_t ring_id;
  uint32_t n_buffers;      /* TOTAL staging buffers (the pool is global) */
  uint32_t free_buffers;   /* free in the global pool                    */
  uint32_t deferred;       /* THIS ring's requests awaiting a buffer     */
  uint64_t submitted;      /* requests ever submitted to this ring      */
  uint64_t completed;      /* requests completed (I/O done, incl. fail) */
  uint32_t inflight_io;    /* submitted - completed: queue depth        */
  int32_t  backend_uring;  /* 1 if this ring runs on io_uring           */
  /* Failure-domain health (io/health.py supervision layer): */
  uint64_t failed;         /* completions with status < 0, cancels
                              excluded (a hot restart's -ECANCELED
                              requeue must not read as device damage)  */
  uint64_t restarts;       /* hot restarts this ring has survived       */
  uint32_t parked;         /* requests parked by stall injection or a
                              restart window (in flight, never
                              dispatched to a backend)                  */
  int32_t  stalled;        /* 1 while stall injection is armed          */
  uint64_t oldest_inflight_ns; /* age of the oldest dispatched-or-parked
                              un-completed request; 0 when idle.  The
                              reap-side stall detector: a completion
                              that never arrives shows up here as an
                              age that only grows.                      */
  /* Zero-copy submission state (PR 12): a silently-unregistered pool or
   * slot table is SLOW, not broken — these gauges make it visible in
   * strom_stat's engine block instead of only in a flamegraph. */
  int32_t  fixed_bufs;     /* staging pool registered as fixed buffers
                              with this ring's uring (pin-once DMA)     */
  int32_t  reg_files;      /* fd slot table registered (hot submissions
                              skip the per-op fget via IOSQE_FIXED_FILE) */
  int32_t  sqpoll;         /* 1 while this ring's submissions are
                              consumed by a kernel SQPOLL thread (uring)
                              or a polling worker (worker-pool analogue)
                              — steady-state submission needs no doorbell */
} strom_ring_info;

int strom_ring_count(strom_engine *eng);
int strom_get_ring_info(strom_engine *eng, uint32_t ring,
                        strom_ring_info *out);

/* Hot ring restart — the failure-domain recovery primitive (the
 * supervision layer in io/health.py drives it; docs/RESILIENCE.md
 * "failure domains").  Sequence:
 *   1. the ring stops dispatching (new submissions park, in order);
 *   2. dispatched in-flight I/O is drained for up to drain_timeout_ns.
 *      If it will not drain the restart ABORTS with -ETIMEDOUT and the
 *      ring resumes exactly as it was (nothing cancelled): an
 *      un-completable kernel I/O cannot be cancelled from userspace
 *      without recycling a live DMA target, so the caller's fallback
 *      is the degraded buffered path, not a forced cancel;
 *   3. the pre-restart stall-parked backlog is completed -ECANCELED —
 *      those requests never reached a backend, so their staging
 *      buffers are clean and the waiter's resubmission (ResilientRead's
 *      retry) is the requeue path;
 *   4. on the io_uring backend the uring is torn down and rebuilt
 *      (fresh fd, fresh SQ/CQ mappings, fresh reaper thread); if the
 *      rebuild fails the ring falls back to the worker-pool backend so
 *      it keeps serving;
 *   5. stall injection is disarmed (the injected wedge heals — that is
 *      the point of the restart) and requests parked during the window
 *      dispatch in order: consumers see one longer wait, never an
 *      error.
 * Returns the number of requests cancelled for requeue (>= 0), or
 * -EINVAL / -EBUSY (restart already running) / -ETIMEDOUT /
 * -ECANCELED (engine stopping). */
int64_t strom_ring_restart(strom_engine *eng, uint32_t ring,
                           uint64_t drain_timeout_ns);

/* Ring-stall fault injection (chaos/stress; see also the env knobs
 * STROM_FAULT_RING_STALL_RING / STROM_FAULT_RING_STALL_AFTER read at
 * engine create): while armed, requests reaching the ring's dispatch
 * point are parked instead of dispatched — a wedged submission queue /
 * hung kernel worker as the waiters see it (completions never arrive,
 * lock-free counters freeze, oldest_inflight_ns grows).  Disarming
 * with on=0 dispatches the parked backlog (a transient stall that
 * healed itself); strom_ring_restart cancels it instead (the requeue
 * path).  Returns 0 or -EINVAL. */
int strom_set_ring_stall(strom_engine *eng, uint32_t ring, int on);

/* Degraded-mode read: a plain synchronous pread on the buffered fd
 * from the CALLING thread — no ring, no uring, no worker pool, no
 * staging buffer.  This is the brown-out path io/health.py falls back
 * to when every ring (or the device behind them) is unhealthy: reduced
 * bandwidth, but alive while the fast path is hot-restarted/probed.
 * Counted as fallback + bounce payload (the page-cache copy is real).
 * Returns bytes read (may be short at EOF) or -errno. */
int64_t strom_read_buffered(strom_engine *eng, int fh, uint64_t offset,
                            uint64_t len, void *dst);

/* Depth-only fast path: submitted - completed from the lock-free
 * per-ring atomics, NO mutex and NO deferral-queue walk — what the QoS
 * scheduler's admission poll calls at dispatch frequency (the full
 * strom_get_ring_info takes pool_mu for buffer/deferral occupancy and
 * belongs in stat dumps, not hot polls).  Returns >= 0, or -EINVAL for
 * a ring index out of range. */
int64_t strom_ring_inflight(strom_engine *eng, uint32_t ring);

/* Engine-independent file eligibility probe (CHECK_FILE analogue). */
int strom_check_file(const char *path, strom_file_info *out);

/* Backing block-device topology of the file at `path` — the other half of
 * the reference's CHECK_FILE verdict (SURVEY.md §3.3: "blockdev must be
 * NVMe, or md-raid0 whose members are all NVMe").  Resolved from sysfs:
 * st_dev -> /sys/dev/block -> partition->parent walk -> md member scan. */
#define STROM_MAX_RAID_MEMBERS 16
typedef struct strom_device_info {
  char    device[64];    /* whole-disk name ("nvme0n1", "md0", "vda");
                            empty when no backing blockdev is visible
                            (overlayfs, tmpfs, network fs)              */
  int32_t is_nvme;       /* whole disk is an NVMe namespace             */
  int32_t is_raid;       /* device is an md array                       */
  int32_t raid_level;    /* numeric md level (0 == raid0); -1 unknown   */
  int32_t n_members;     /* md member count (whole-disk resolved)       */
  int32_t rotational;    /* /sys/block/<dev>/queue/rotational; -1 unknown */
  int32_t nvme_backed;   /* the CHECK_FILE verdict: NVMe, or md-raid0
                            striped over all-NVMe members               */
  char    members[STROM_MAX_RAID_MEMBERS][64];
} strom_device_info;

/* Returns 0 (with device[0]=='\0' if unresolvable) or -errno when `path`
 * itself cannot be stat'ed. */
int strom_resolve_device(const char *path, strom_device_info *out);

/* File-offset -> physical-extent map, the analogue of the reference's
 * in-kernel extent walk that turns (inode, offset, len) into NVMe LBAs
 * (SURVEY.md §3.1).  Backed by the FIEMAP ioctl; filesystems without
 * FIEMAP yield one synthetic whole-file extent (physical == 0, flags =
 * STROM_EXTENT_SYNTHETIC) — the logical analogue of the reference's
 * page-cache fallback: the range is still readable, just not physically
 * addressable. */
#define STROM_EXTENT_SYNTHETIC 0x80000000u
typedef struct strom_extent {
  uint64_t logical;   /* byte offset in the file                        */
  uint64_t physical;  /* byte offset on the backing device (0 unknown)  */
  uint64_t length;    /* extent length in bytes                         */
  uint32_t flags;     /* raw fiemap fe_flags (| STROM_EXTENT_SYNTHETIC) */
  uint32_t pad;
} strom_extent;

/* Fills up to `max` extents covering [0, file_size). Returns the number
 * of extents written (>= 0) or -errno. */
int strom_file_extents(const char *path, strom_extent *out, uint32_t max);

/* md-raid0 stripe attribution: how many bytes of the physical span
 * [phys_off, phys_off + len) land on each of the n_members striped
 * devices (stripe chunk `chunk` bytes, member of chunk k = k mod n)?
 * Adds into out_bytes[0..n_members).  Closed-form over full stripe
 * periods plus a <= 2*n_members remainder walk — O(members), not
 * O(len/chunk).  Pure function: the per-member byte counters behind
 * `strom_stat --device` (the striped-scaling attribution the
 * reference's 6-10 GB/s md-raid0 claim implies, SURVEY.md §6) are
 * buildable and testable without raid hardware. */
void strom_stripe_attr(uint64_t phys_off, uint64_t len, uint64_t chunk,
                       uint32_t n_members, uint64_t *out_bytes);

/* Staging-pool introspection — the LIST_GPU_MEMORY / INFO_GPU_MEMORY
 * analogue (SURVEY.md §2 "GPU memory mapper"): the reference enumerates
 * pinned GPU mappings; we report the pinned staging pool and its
 * occupancy. */
typedef struct strom_pool_info {
  uint32_t n_buffers;     /* total staging buffers                     */
  uint32_t free_buffers;  /* currently unassigned                      */
  uint64_t buf_bytes;     /* payload capacity per buffer               */
  uint64_t pool_bytes;    /* total mapped bytes incl. alignment slack  */
  int32_t  locked;        /* 1 if mlock'd (pinned)                     */
  int32_t  queue_depth;
  uint32_t in_flight;     /* submitted, not yet released               */
  uint32_t deferred;      /* submitted, waiting for a free buffer      */
  int32_t  fixed_bufs;    /* 1 if pool registered as io_uring fixed
                             buffers (pin-once, READ_FIXED/WRITE_FIXED) */
  uint32_t pad;
  uint64_t pool_base;     /* staging pool base address: lets callers
                             PROVE a returned view aliases the pool
                             (zero-copy up to the device boundary)      */
} strom_pool_info;

void strom_get_pool_info(strom_engine *eng, strom_pool_info *out);

/* Open a file for engine I/O. Tries O_DIRECT first; transparently falls
 * back to buffered (counted per-request). Returns fh >= 0 or -errno.
 * flags: bit 0 = writable; bit 1 = force buffered I/O (debug/testing knob,
 * like the reference's module params — SURVEY.md §5 Config/flags). */
int strom_open(strom_engine *eng, const char *path, int flags);
#define STROM_OPEN_WRITABLE 1
#define STROM_OPEN_NO_DIRECT 2
int strom_close(strom_engine *eng, int fh);
int64_t strom_file_size(strom_engine *eng, int fh);
int strom_file_is_direct(strom_engine *eng, int fh);

/* Stable identity of the file BEHIND the open fh, via fstat on the
 * engine's own descriptor (never the path — a rename racing the open
 * could attribute one inode's bytes to another's identity): out =
 * {st_dev, st_ino, mtime_ns, size}.  The pinned-host cache tier keys
 * its lines by this. */
int strom_file_ident(strom_engine *eng, int fh, uint64_t out[4]);

/* Submit an async read of [offset, offset+len). len must be
 * <= buf_bytes. Unaligned offset/len are handled by reading the enclosing
 * aligned span; the completion's data pointer is pre-offset (no copy).
 * Blocks if no staging buffer is free. Returns req_id >= 0 or -errno. */
int64_t strom_submit_read(strom_engine *eng, int fh, uint64_t offset,
                          uint64_t len);

/* One extent of a vectored submission (strom_submit_readv). */
typedef struct strom_rd_ext {
  int32_t  fh;
  uint32_t pad;
  uint64_t offset;
  uint64_t length;     /* must be <= buf_bytes */
} strom_rd_ext;

/* Vectored read submission: stage every extent's SQE, then ring the
 * doorbell with a SINGLE io_uring_enter (the thread-pool backend queues
 * all extents under one lock hold) — the per-request ioctl/syscall
 * amortization the reference gets from multi-chunk MEMCPY_SSD2GPU
 * commands (SURVEY.md §3.1).  Validation is atomic: on any invalid
 * extent (-EINVAL over-size, -EBADF unknown fh) NOTHING is submitted.
 * On success returns 0 and fills out_ids[0..n) with per-extent request
 * ids (wait/release each exactly like strom_submit_read's).  Extents
 * whose buffers are exhausted defer, never block, preserving
 * submission order. */
int strom_submit_readv(strom_engine *eng, const strom_rd_ext *exts,
                       uint32_t n, int64_t *out_ids);

/* Ring-pinned variants: identical semantics, but the caller (the QoS
 * scheduler) names the ring instead of the engine's round-robin pick.
 * A whole readv batch lands on ONE ring — one doorbell, one deferral
 * queue, no cross-ring interleave within the batch.  -EINVAL for a
 * ring index out of range. */
int64_t strom_submit_read_ring(strom_engine *eng, uint32_t ring, int fh,
                               uint64_t offset, uint64_t len);
int strom_submit_readv_ring(strom_engine *eng, uint32_t ring,
                            const strom_rd_ext *exts, uint32_t n,
                            int64_t *out_ids);

/* Wait until req_id completes; fills *out. The buffer stays owned by the
 * request until strom_release. */
int strom_wait(strom_engine *eng, int64_t req_id, strom_completion *out);

/* Bounded wait: -ETIMEDOUT after timeout_ns if the request has not
 * completed (request stays live; retry or diagnose — the failure-
 * DETECTION half of the recovery story). */
int strom_wait_timeout(strom_engine *eng, int64_t req_id,
                       strom_completion *out, uint64_t timeout_ns);

/* Return the request's staging buffer to the pool. */
int strom_release(strom_engine *eng, int64_t req_id);

/* Async write of len bytes from src to [offset, offset+len) (checkpoint /
 * HBM->NVMe path). If src and offset/len are alignment-conformant the
 * write is O_DIRECT straight from src (zero copy); otherwise it bounces
 * through a pool buffer (counted). Returns req_id; wait with strom_wait;
 * release with strom_release. */
int64_t strom_submit_write(strom_engine *eng, int fh, uint64_t offset,
                           const void *src, uint64_t len);

/* Ring-pinned write (strom_submit_read_ring's mirror): the caller
 * names the ring instead of the engine's round-robin pick — how the
 * supervision layer keeps checkpoint/KV writes off a ring whose
 * breaker is open.  -EINVAL for a ring index out of range. */
int64_t strom_submit_write_ring(strom_engine *eng, uint32_t ring, int fh,
                                uint64_t offset, const void *src,
                                uint64_t len);

void strom_get_stats(strom_engine *eng, strom_stats_blk *out);
void strom_reset_stats(strom_engine *eng);
/* Atomically read-and-zero every counter (per-counter exchange): no
 * increment can be lost between the read and the reset. */
void strom_drain_stats(strom_engine *eng, strom_stats_blk *out);

/* Introspection for tests/bench. */
int strom_backend_is_uring(strom_engine *eng);

/* crc32c (Castagnoli), for TFRecord integrity checks: slice-by-8 software
 * implementation, hardware SSE4.2 path when the CPU supports it.
 * `crc` is the running value (0 to start); returns the updated crc. */
uint32_t strom_crc32c(const void *data, uint64_t len, uint32_t crc);

/* Pinned host-DRAM cache arena (io/hostcache.py — the tier between NVMe
 * and HBM).  Engine-independent, like strom_crc32c: the Python tier owns
 * line bookkeeping; this is just the mapped+pinned backing store and the
 * completion->line copy primitive.
 *
 * strom_hostcache_arena_create maps `bytes` of anonymous memory,
 * pre-faults it (MAP_POPULATE: a fill must memcpy, never page-fault, so
 * the staging buffer it drains recycles at DRAM speed) and — when
 * `lock_pages` — best-effort mlocks it so cache hits can never stall on
 * swapped-out lines.  *locked_out (optional) reports whether the mlock
 * held (RLIMIT_MEMLOCK may refuse; the arena still works, unpinned).
 * Returns NULL with errno set when the mapping itself fails.
 *
 * strom_hostcache_copy is the fill primitive: memcpy a completed staging
 * view into a line.  Called via ctypes, it runs with the GIL dropped —
 * the copy happens off the Python hot path exactly like the engine's own
 * bounce copies. */
void *strom_hostcache_arena_create(uint64_t bytes, int lock_pages,
                                   int32_t *locked_out);
void strom_hostcache_arena_destroy(void *base, uint64_t bytes);
void strom_hostcache_copy(void *dst, const void *src, uint64_t bytes);

/* Native tar shard indexer — the header walk that builds the
 * WebDataset sample map (formats/wds.py) without a Python-loop per
 * member: ustar (name+prefix), GNU longname ('L'), and pax ('x'
 * path=/size= overrides) are understood; directories and other
 * non-file members are skipped.  On success returns the number of
 * regular-file entries and sets *out to a malloc'd packed buffer of
 *
 *   u64 data_offset | u64 size | u32 name_len | name bytes
 *
 * records totalling *out_bytes (caller frees with
 * strom_tar_index_free).  Negative errno on IO error; -EBADMSG for a
 * malformed archive (bad checksum, truncated header/data, broken pax
 * records) and for member names over 4096 bytes — always loud, never
 * a silent partial or truncated-key index. */
int64_t strom_tar_index(const char *path, uint8_t **out,
                        uint64_t *out_bytes);
void strom_tar_index_free(uint8_t *buf);

#ifdef __cplusplus
}
#endif
#endif /* STROM_IO_H */
