/* strom_io.cc — the strom-io engine: NVMe -> locked staging buffers with
 * zero host-side payload copies.
 *
 * This is the TPU build's equivalent of the reference's nvme_strom.c kernel
 * module (SURVEY.md §2: "SSD→GPU DMA engine", ~1.5-2k LoC of extent walking
 * + async NVMe command submission).  We cannot load kernel modules on TPU
 * VMs, so the same property — payload bytes never memcpy'd by the host CPU —
 * is obtained with io_uring + O_DIRECT: the NVMe controller DMAs file data
 * straight into this engine's mlock'd, alignment-conformant staging buffers,
 * which are then handed (by pointer, never by copy) to the JAX bridge as the
 * source of the host->TPU PCIe transfer.
 *
 * Design notes:
 *  - io_uring is driven by raw syscalls (425/426) — no liburing dependency.
 *  - A request for an unaligned [offset, len) range reads the enclosing
 *    aligned span and returns a pointer *into* the buffer (data = buf +
 *    head_slack): the reference handles the same problem with sector-aligned
 *    extent chunking in-kernel (SURVEY.md §3.1).
 *  - Files that reject O_DIRECT (tmpfs/overlayfs) or reads that come back
 *    EINVAL take the buffered-read fallback, counted in bytes_fallback and
 *    bounce_bytes — the analogue of the reference's page-cache fallback
 *    chunks, which are also host-copied (SURVEY.md §3.1 "page-cache
 *    fallback").
 *  - Stats counters mirror STROM_IOCTL__STAT_INFO (SURVEY.md §5).
 */

#include "strom_io.h"

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <limits.h>
#include <stdio.h>
#include <stdlib.h>
#include <linux/fiemap.h>
#include <linux/fs.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <sys/statfs.h>
#include <sys/syscall.h>
#include <sys/sysmacros.h>
#include <time.h>
#include <unistd.h>

/* ---------------- raw io_uring plumbing (no liburing) ---------------- */

#ifndef __NR_io_uring_setup
#define __NR_io_uring_setup 425
#endif
#ifndef __NR_io_uring_enter
#define __NR_io_uring_enter 426
#endif
#ifndef __NR_io_uring_register
#define __NR_io_uring_register 427
#endif

struct io_sqring_offsets_ {
  uint32_t head, tail, ring_mask, ring_entries, flags, dropped, array, resv1;
  uint64_t resv2;
};
struct io_cqring_offsets_ {
  uint32_t head, tail, ring_mask, ring_entries, overflow, cqes, flags, resv1;
  uint64_t resv2;
};
struct io_uring_params_ {
  uint32_t sq_entries, cq_entries, flags, sq_thread_cpu, sq_thread_idle;
  uint32_t features, wq_fd, resv[3];
  io_sqring_offsets_ sq_off;
  io_cqring_offsets_ cq_off;
};
struct io_uring_sqe_ {
  uint8_t opcode, flags;
  uint16_t ioprio;
  int32_t fd;
  uint64_t off, addr;
  uint32_t len, rw_flags;
  uint64_t user_data;
  uint16_t buf_index, personality;
  int32_t splice_fd_in;
  uint64_t pad2[2];
};
struct io_uring_cqe_ {
  uint64_t user_data;
  int32_t res;
  uint32_t flags;
};

static constexpr uint64_t kOffSqRing = 0ULL;
static constexpr uint64_t kOffCqRing = 0x8000000ULL;
static constexpr uint64_t kOffSqes = 0x10000000ULL;
static constexpr uint32_t kFeatSingleMmap = 1u << 0;
static constexpr uint32_t kEnterGetevents = 1u << 0;
/* SQPOLL plumbing: IORING_SETUP_SQPOLL asks the kernel for a dedicated
 * SQ-consuming thread; while it is awake submissions need NO syscall at
 * all — the tail store IS the submission (the natural endpoint of the
 * "one doorbell" arc: zero doorbells).  When the thread idles out
 * (sq_thread_idle ms) the SQ ring flags raise NEED_WAKEUP and the next
 * submit pays one io_uring_enter(SQ_WAKEUP). */
static constexpr uint32_t kSetupSqpoll = 1u << 1;
static constexpr uint32_t kSqNeedWakeup = 1u << 0;
static constexpr uint32_t kEnterSqWakeup = 1u << 1;
static constexpr uint8_t kOpNop = 0, kOpRead = 22, kOpWrite = 23;
/* Fixed-buffer variants: the kernel pins the staging pool ONCE at
 * registration instead of get_user_pages()-pinning every I/O — the same
 * pin-once pattern as the reference's MAP_GPU_MEMORY (SURVEY.md §3.2). */
static constexpr uint8_t kOpReadFixed = 4, kOpWriteFixed = 5;
static constexpr uint32_t kRegisterBuffers = 0;
/* Registered files: a slot table the kernel resolves instead of a per-op
 * fget()/fput() on the raw fd — IOSQE_FIXED_FILE turns sqe->fd into a
 * table index.  The table registers sparse (-1 slots) at ring init and
 * is updated at strom_open/strom_close. */
static constexpr uint32_t kRegisterFiles = 2;
static constexpr uint32_t kRegisterFilesUpdate = 6;
static constexpr uint8_t kSqeFixedFile = 1u << 0;
static constexpr uint64_t kShutdownUserData = ~0ULL;

struct io_uring_files_update_ {
  uint32_t offset, resv;
  uint64_t fds;   /* pointer to int32_t fds */
};

struct Uring {
  int fd = -1;
  uint32_t *sq_head = nullptr, *sq_tail = nullptr, *sq_mask = nullptr;
  uint32_t *sq_array = nullptr;
  uint32_t *sq_flags = nullptr;   /* NEED_WAKEUP lives here (SQPOLL) */
  uint32_t *cq_head = nullptr, *cq_tail = nullptr, *cq_mask = nullptr;
  io_uring_cqe_ *cqes = nullptr;
  io_uring_sqe_ *sqes = nullptr;
  void *sq_ring_ptr = nullptr, *cq_ring_ptr = nullptr;
  size_t sq_ring_sz = 0, cq_ring_sz = 0, sqes_sz = 0;
  uint32_t sq_entries = 0;
  bool single_mmap = false;
  bool fixed_bufs = false;   /* staging pool registered with the kernel */
  /* fd slot table registered (FIXED_FILE).  Atomic: cleared under
   * files_mu by a refused slot update while dispatchers read it under
   * their ring mutex — a plain bool would be a (benign) race. */
  std::atomic<bool> reg_files{false};
  bool sqpoll = false;       /* IORING_SETUP_SQPOLL accepted            */
  /* requested mode, preserved across a hot restart's teardown/re-init */
  bool want_sqpoll = false;
  uint32_t sqpoll_idle_ms = 50;
  /* submission-doorbell accounting (engine-owned atomics; see
   * strom_stats_blk.submit_enters): enters = doorbells actually rung,
   * elided = doorbells SQPOLL made unnecessary */
  std::atomic<uint64_t> *c_enters = nullptr, *c_elided = nullptr;

  void count_enter() {
    if (c_enters) c_enters->fetch_add(1, std::memory_order_relaxed);
  }
  void count_elided() {
    if (c_elided) c_elided->fetch_add(1, std::memory_order_relaxed);
  }
  /* SQEs published to the ring but not yet consumed by io_uring_enter
   * (enter can fail with EINTR/EBUSY after the tail was advanced; the
   * entry then MUST be submitted by a later enter, never abandoned —
   * an abandoned SQE would be consumed by the next enter and DMA into
   * a buffer that has since been reassigned). */
  std::atomic<uint32_t> unsubmitted{0};

  bool init(uint32_t entries) {
    io_uring_params_ p;
    memset(&p, 0, sizeof(p));
    int r = -1;
    sqpoll = false;
    if (want_sqpoll) {
      /* SQPOLL first; refused (old kernel, privileges pre-5.11) falls
       * back to the plain ring — slower, never broken. */
      p.flags = kSetupSqpoll;
      p.sq_thread_idle = sqpoll_idle_ms;
      r = (int)syscall(__NR_io_uring_setup, entries, &p);
      if (r >= 0) sqpoll = true;
      else memset(&p, 0, sizeof(p));
    }
    if (r < 0) r = (int)syscall(__NR_io_uring_setup, entries, &p);
    if (r < 0) return false;
    fd = r;
    sq_entries = p.sq_entries;
    sq_ring_sz = p.sq_off.array + p.sq_entries * sizeof(uint32_t);
    cq_ring_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe_);
    single_mmap = (p.features & kFeatSingleMmap) != 0;
    if (single_mmap && cq_ring_sz > sq_ring_sz) sq_ring_sz = cq_ring_sz;
    sq_ring_ptr = mmap(nullptr, sq_ring_sz, PROT_READ | PROT_WRITE,
                       MAP_SHARED | MAP_POPULATE, fd, kOffSqRing);
    if (sq_ring_ptr == MAP_FAILED) { close(fd); fd = -1; return false; }
    if (single_mmap) {
      cq_ring_ptr = sq_ring_ptr;
      cq_ring_sz = sq_ring_sz;
    } else {
      cq_ring_ptr = mmap(nullptr, cq_ring_sz, PROT_READ | PROT_WRITE,
                         MAP_SHARED | MAP_POPULATE, fd, kOffCqRing);
      if (cq_ring_ptr == MAP_FAILED) { teardown(); return false; }
    }
    sqes_sz = p.sq_entries * sizeof(io_uring_sqe_);
    sqes = (io_uring_sqe_ *)mmap(nullptr, sqes_sz, PROT_READ | PROT_WRITE,
                                 MAP_SHARED | MAP_POPULATE, fd, kOffSqes);
    if (sqes == MAP_FAILED) { sqes = nullptr; teardown(); return false; }
    auto *sqb = (uint8_t *)sq_ring_ptr;
    sq_head = (uint32_t *)(sqb + p.sq_off.head);
    sq_tail = (uint32_t *)(sqb + p.sq_off.tail);
    sq_mask = (uint32_t *)(sqb + p.sq_off.ring_mask);
    sq_array = (uint32_t *)(sqb + p.sq_off.array);
    sq_flags = (uint32_t *)(sqb + p.sq_off.flags);
    auto *cqb = (uint8_t *)cq_ring_ptr;
    cq_head = (uint32_t *)(cqb + p.cq_off.head);
    cq_tail = (uint32_t *)(cqb + p.cq_off.tail);
    cq_mask = (uint32_t *)(cqb + p.cq_off.ring_mask);
    cqes = (io_uring_cqe_ *)(cqb + p.cq_off.cqes);
    return true;
  }

  /* Register the staging pool as fixed buffers (one iovec per staging
   * buffer; SQE buf_index selects one). Soft-fail: EOPNOTSUPP/ENOMEM
   * (old kernel, RLIMIT_MEMLOCK) just leaves the non-fixed opcodes. */
  void try_register(uint8_t *pool, uint64_t buf_cap, uint32_t n) {
    std::vector<struct iovec> iov(n);
    for (uint32_t i = 0; i < n; i++) {
      iov[i].iov_base = pool + (uint64_t)i * buf_cap;
      iov[i].iov_len = buf_cap;
    }
    fixed_bufs = syscall(__NR_io_uring_register, fd, kRegisterBuffers,
                         iov.data(), n) == 0;
  }

  /* Register the fd slot table (sparse: -1 slots are empty).  Soft-fail
   * like try_register: kernels without sparse REGISTER_FILES support
   * just keep resolving raw fds per op. */
  void try_register_files(const int32_t *fds, uint32_t n) {
    reg_files = syscall(__NR_io_uring_register, fd, kRegisterFiles,
                        fds, n) == 0;
  }

  /* Point one slot of the registered table at `newfd` (-1 clears).
   * Returns false when the kernel refused — the caller downgrades that
   * file to raw-fd submission rather than risking a stale slot. */
  bool update_file(uint32_t slot, int32_t newfd) {
    if (!reg_files) return false;
    io_uring_files_update_ up;
    up.offset = slot;
    up.resv = 0;
    up.fds = (uint64_t)(uintptr_t)&newfd;
    return syscall(__NR_io_uring_register, fd, kRegisterFilesUpdate,
                   &up, 1) == 1;
  }

  /* Wait until the kernel has CONSUMED every published SQE (sq_head
   * caught up).  An unconsumed SQE carrying IOSQE_FIXED_FILE resolves
   * its slot at consumption time — so a slot must not be recycled to
   * another file while any SQE referencing it is still in the SQ.
   * Bounded: returns false if the queue would not drain (the caller
   * then leaks the slot instead of recycling it — safe, never
   * wrong). */
  bool drain_sq() {
    if (fd < 0) return true;
    for (int i = 0; i < 100000; i++) {
      if (!sqpoll) flush();
      else sqpoll_kick(/*count_elide=*/false);
      uint32_t head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
      uint32_t tail = __atomic_load_n(sq_tail, __ATOMIC_ACQUIRE);
      if (head == tail &&
          unsubmitted.load(std::memory_order_acquire) == 0)
        return true;
      usleep(10);
    }
    return false;
  }

  void teardown() {
    if (sqes) munmap(sqes, sqes_sz);
    if (cq_ring_ptr && cq_ring_ptr != sq_ring_ptr) munmap(cq_ring_ptr, cq_ring_sz);
    if (sq_ring_ptr) munmap(sq_ring_ptr, sq_ring_sz);
    if (fd >= 0) close(fd);
    sqes = nullptr; cq_ring_ptr = sq_ring_ptr = nullptr; fd = -1;
    sqpoll = false; reg_files = false;
  }

  /* SQPOLL doorbell: the kernel thread consumes published SQEs on its
   * own; only when it idled out (NEED_WAKEUP raised) does the submitter
   * pay one io_uring_enter(SQ_WAKEUP).  Every skipped doorbell counts —
   * that is the syscall elision the whole mode exists for.
   * ``count_elide=false`` for polls that do not correspond to a
   * published SQE (the SQ-full spin), so backpressure noise cannot
   * inflate the elision counter. */
  void sqpoll_kick(bool count_elide = true) {
    /* Full fence between the tail store and the NEED_WAKEUP load: the
     * SQ thread sets NEED_WAKEUP after seeing an empty queue, and a
     * StoreLoad reordering here (legal on x86 AND arm) could read the
     * flags from before it slept — doorbell elided, SQE stranded, the
     * waiter hangs.  This is the io_uring_smp_mb() liburing documents
     * for exactly this handshake. */
    __atomic_thread_fence(__ATOMIC_SEQ_CST);
    if (__atomic_load_n(sq_flags, __ATOMIC_ACQUIRE) & kSqNeedWakeup) {
      syscall(__NR_io_uring_enter, fd, 0, 0, kEnterSqWakeup, nullptr, 0);
      count_enter();
    } else if (count_elide) {
      count_elided();
    }
  }

  /* Push any published-but-unconsumed SQEs into the kernel. Safe to call
   * from any thread. Returns 0 when the backlog is drained. */
  int flush() {
    if (sqpoll) {
      /* nothing tracked in `unsubmitted` under SQPOLL (publishing IS
       * submitting); just make sure the poller is awake */
      if (unsubmitted.load(std::memory_order_acquire) == 0) {
        sqpoll_kick();
        return 0;
      }
    }
    for (int attempt = 0; attempt < 1000; attempt++) {
      uint32_t n = unsubmitted.load(std::memory_order_acquire);
      if (n == 0) return 0;
      int r = (int)syscall(__NR_io_uring_enter, fd, n, 0, 0, nullptr, 0);
      if (r >= 0) count_enter();
      if (r > 0) {
        unsubmitted.fetch_sub((uint32_t)r, std::memory_order_acq_rel);
        continue;
      }
      if (r < 0 && errno != EINTR && errno != EAGAIN && errno != EBUSY)
        return -errno;
      if (r == 0 || errno == EAGAIN || errno == EBUSY) usleep(10);
    }
    return -EBUSY; /* backlog persists; a later flush will retry it */
  }

  /* Caller must serialise submissions (engine holds a mutex). Returns 0 or
   * -errno. The SQE is always published; a transient enter failure leaves
   * it queued for the next flush rather than failing the request.
   * ``flush_now = false`` stages the SQE without ringing the doorbell —
   * the vectored submit path publishes a whole batch, then pays ONE
   * io_uring_enter via flush() (an SQ that fills mid-batch still flushes
   * inline below; correctness never depends on the deferred flush). */
  int submit(uint8_t opcode, int fd_, uint64_t off, void *addr, uint32_t len,
             uint64_t user_data, uint16_t buf_index = 0,
             bool flush_now = true, uint8_t sqe_flags = 0) {
    uint32_t tail = *sq_tail;
    uint32_t head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
    if (tail - head >= sq_entries) {
      /* SQ full: nudge the kernel and spin-wait (bounded by in-flight I/O). */
      for (int i = 0; i < 100000 && tail - head >= sq_entries; i++) {
        if (sqpoll) sqpoll_kick(/*count_elide=*/false);  /* poller
                                          drains the SQ; spin polls are
                                          not elided doorbells */
        else {
          flush();
          syscall(__NR_io_uring_enter, fd, 0, 0, 0, nullptr, 0);
        }
        head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
      }
      if (tail - head >= sq_entries) return -EBUSY;
    }
    uint32_t idx = tail & *sq_mask;
    io_uring_sqe_ *sqe = &sqes[idx];
    memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = opcode;
    sqe->flags = sqe_flags;
    sqe->fd = fd_;
    sqe->off = off;
    sqe->addr = (uint64_t)addr;
    sqe->len = len;
    sqe->user_data = user_data;
    sqe->buf_index = buf_index;
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
    if (sqpoll) {
      /* publishing IS submitting: the SQ thread consumes the tail on
       * its own.  `unsubmitted` stays 0 — there is no backlog an
       * abandoned enter could strand. */
      if (flush_now) sqpoll_kick();
      return 0;
    }
    unsubmitted.fetch_add(1, std::memory_order_acq_rel);
    if (flush_now) flush();
    return 0; /* published: the op WILL reach the kernel */
  }

  /* Blocks for >=1 completion; invokes fn(user_data, res) per CQE.
   * Returns number consumed, or -errno. */
  template <typename F>
  int reap(F &&fn) {
    if (unsubmitted.load(std::memory_order_acquire) > 0) flush();
    uint32_t head = *cq_head;
    uint32_t tail = __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE);
    if (head == tail) {
      int r = (int)syscall(__NR_io_uring_enter, fd, 0, 1, kEnterGetevents,
                           nullptr, 0);
      if (r < 0 && errno != EINTR) return -errno;
      tail = __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE);
    }
    int n = 0;
    while (head != tail) {
      io_uring_cqe_ *cqe = &cqes[head & *cq_mask];
      fn(cqe->user_data, cqe->res);
      head++;
      n++;
    }
    __atomic_store_n(cq_head, head, __ATOMIC_RELEASE);
    return n;
  }
};

/* ---------------------------- engine ---------------------------- */

struct RingCtx;

namespace {

inline uint64_t align_down(uint64_t x, uint64_t a) { return x & ~(a - 1); }
inline uint64_t align_up(uint64_t x, uint64_t a) { return (x + a - 1) & ~(a - 1); }

struct FileEnt {
  int fd_direct = -1;   /* -1 when the fs refused O_DIRECT */
  int fd_buffered = -1;
  int64_t size = 0;
  bool writable = false;
  /* registered-file slots (-1 = not in the table): hot submissions use
   * IOSQE_FIXED_FILE with the slot index so the kernel skips the
   * per-op fget/fput of the raw fd */
  int slot_direct = -1;
  int slot_buffered = -1;
};

enum class ReqState { kInflight, kDone };

inline uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

/* Is [offset, offset+len) fully resident in the page cache?  The
 * reference's kernel module checks this per block and returns resident
 * blocks to userspace instead of issuing NVMe reads (SURVEY.md §3.1);
 * here a transient mmap + mincore answers the same question from
 * userspace without faulting anything in (mmap does not populate).
 * preadv2(RWF_NOWAIT) would also work but performs the copy during the
 * probe — under the submit lock that would stall other submitters. */
static bool span_resident(int fd, uint64_t offset, uint64_t len) {
  if (len == 0) return false;
  static const uint64_t pg = (uint64_t)sysconf(_SC_PAGESIZE);
  uint64_t m_off = align_down(offset, pg);
  uint64_t m_len = offset + len - m_off;
  void *m = mmap(nullptr, m_len, PROT_READ, MAP_SHARED, fd, (off_t)m_off);
  if (m == MAP_FAILED) return false;
  size_t npg = (size_t)((m_len + pg - 1) / pg);
  bool all = true;
  std::vector<unsigned char> vec(npg);
  if (mincore(m, m_len, vec.data()) != 0) {
    all = false;
  } else {
    for (size_t i = 0; i < npg; i++)
      if (!(vec[i] & 1)) { all = false; break; }
  }
  munmap(m, m_len);
  return all;
}

struct Req {
  int64_t id = 0;
  RingCtx *rc = nullptr;               /* owning ring                 */
  int fh = -1;
  uint64_t offset = 0, len = 0;        /* caller's request            */
  uint64_t a_off = 0, a_len = 0;       /* aligned span actually read  */
  int buf_idx = -1;                    /* -1: zero-copy direct write  */
  uint8_t *buf = nullptr;              /* base of staging buffer      */
  const void *wsrc = nullptr;          /* write source (write path)   */
  bool is_write = false;
  bool direct = false;                 /* submitted O_DIRECT          */
  bool was_fallback = false;
  bool dispatched = false;             /* handed to a backend (uring
                                          SQE staged / worker queued) —
                                          a restart's drain waits ONLY
                                          for these; deferred and
                                          parked requests hold no
                                          kernel-visible I/O          */
  bool parked = false;                 /* on the ring's stall/restart
                                          park queue                  */
  bool planned_resident = false;       /* submit-time mincore probe chose
                                          the page-cache path on purpose */
  ReqState state = ReqState::kInflight;
  int status = 0;                      /* 0 or -errno                 */
  uint64_t done_len = 0;               /* payload bytes transferred   */
  uint64_t t_submit = 0, t_complete = 0; /* CLOCK_MONOTONIC ns        */
};

}  // namespace

/* One submission ring: an io_uring instance (or worker pool) with its
 * own completion reaping, its own slice of the staging pool, its own
 * deferral queue, and its own lock.  The engine shards into N of these
 * (strom_engine_create_rings) so concurrent traffic classes never
 * serialize behind one doorbell or one pool mutex; request ids carry
 * the ring index in their low STROM_RING_ID_BITS bits, so wait/release
 * route without any shared map. */
struct RingCtx {
  strom_engine *eng = nullptr;
  uint32_t idx = 0;
  Uring ring;
  bool use_uring = false;
  std::thread reaper;
  std::vector<std::thread> workers;
  std::deque<Req *> work_q;             /* thread-pool backend queue */

  std::mutex mu;
  std::condition_variable cv_done;      /* request completed       */
  std::condition_variable cv_work;      /* thread-pool work queue  */
  std::unordered_map<int64_t, Req *> reqs;

  /* Lock-free per-ring counters: the QoS scheduler polls queue depth
   * (submitted - completed) at dispatch frequency without ever taking
   * the ring mutex. */
  std::atomic<uint64_t> rg_sub{0}, rg_comp{0};
  /* Failure-domain health (strom_ring_info / io/health.py): completions
   * with a real error (cancels excluded) and hot restarts survived. */
  std::atomic<uint64_t> rg_fail{0}, rg_restarts{0};

  /* Stall injection + restart window (all under mu): while `stalled`
   * (chaos) or `restarting`, requests reaching dispatch park here in
   * order instead of going to a backend — the deterministic stand-in
   * for a wedged submission queue.  strom_ring_restart cancels the
   * backlog (-ECANCELED, the requeue path); strom_set_ring_stall(.., 0)
   * dispatches it (a stall that healed itself). */
  std::deque<Req *> park_q;
  bool stalled = false;
  bool restarting = false;
  uint64_t stall_after = 0;   /* clean dispatches before the stall bites */
  uint64_t stall_seen = 0;

  /* Worker-pool SQPOLL analogue (under mu): workers POLL the work
   * queue for sq_idle_ns before sleeping, and a dispatch that finds a
   * poller awake skips the wakeup notification entirely — the same
   * doorbell-elision state machine as the kernel SQ thread, same
   * counters, so the mode is benchable and testable on hosts without
   * io_uring. */
  bool sq_poll = false;
  uint64_t sq_idle_ns = 0;
  int poll_workers = 0;       /* workers currently awake-polling */

  void complete_locked(Req *r);
  void complete(Req *r) {
    std::lock_guard<std::mutex> g(mu);
    complete_locked(r);
  }
  void dispatch_locked(Req *r, bool flush_now = true);
  void reaper_loop();
  void worker_loop();
};

struct strom_engine {
  uint32_t queue_depth, n_buffers, alignment;  /* PER RING */
  uint32_t n_rings = 1;
  uint64_t buf_bytes;     /* payload capacity */
  uint64_t buf_cap;       /* buf_bytes + 2*alignment slack */
  bool locked = false;
  bool owns_pool = true;  /* false: pool is an arena carve the caller
                             owns — never munmap'd here (PR 12)       */
  /* Zero-copy submission modes (env at create; see strom_io.h): */
  bool sqpoll_enabled = false;
  uint32_t sqpoll_idle_ms = 50;
  bool reg_files_enabled = true;
  std::atomic<bool> stopping{false};

  uint8_t *pool = nullptr;   /* ONE mapping, ONE fungible pool: any ring
                                may stage into any buffer (each ring
                                registers the whole pool as fixed
                                buffers).  A global pool is load-bearing
                                for deadlock freedom: consumers size
                                their in-flight window against the WHOLE
                                pool, and a batch pinned to one ring
                                must never deadlock behind a per-ring
                                slice smaller than that window. */
  size_t pool_sz = 0;
  std::mutex pool_mu;        /* leaf lock (may nest under a ring mutex):
                                guards free_bufs + the GLOBAL deferral
                                FIFO, which preserves engine-wide
                                submission order for buffer handoff */
  std::vector<int> free_bufs;
  std::deque<Req *> defer_q; /* submitted, awaiting a buffer (any ring) */
  std::vector<std::unique_ptr<RingCtx>> rings;
  std::atomic<uint64_t> rr{0};          /* round-robin ring pick  */
  std::atomic<int64_t> next_req{1};
  std::mutex restart_mu;                /* serializes hot ring restarts
                                           against each other and
                                           against engine destroy (held
                                           across the whole restart —
                                           outermost, never taken under
                                           a ring mutex) */

  std::mutex files_mu;                  /* leaf lock: may be taken while
                                           a ring mutex is held, never
                                           the other way around */
  std::unordered_map<int, FileEnt> files;
  int next_fh = 1;
  /* Registered-file slot table (under files_mu): the canonical fd-per-
   * slot view every uring registered at init and updates at open/close
   * — and what a hot restart re-registers from after its rebuild. */
  std::vector<int32_t> reg_fds;
  std::vector<uint32_t> reg_free;

  /* Update one slot on every registered ring.  Caller holds
   * restart_mu, NOT files_mu: the syscall touches each Uring's fd,
   * which only a hot restart ever tears down/rebuilds — restart_mu is
   * exactly the lock that excludes restarts (taking a ring mutex here
   * instead would invert the ring-mutex→files_mu order).  A ring that
   * refuses the update drops its reg_files flag — raw-fd submission
   * is always correct, a stale slot never is.  (On pre-5.11 kernels a
   * raw fd on a SQPOLL ring completes -EBADF; the reaper's sync
   * rescue path then serves the op buffered — degraded, never
   * wrong.) */
  void reg_update_all(uint32_t slot, int32_t newfd);
  int32_t reg_alloc_slot(int fd);      /* files_mu held; -1 = full    */
  void reg_clear_slot(int32_t slot);   /* files_mu held: table -1,
                                          slot NOT yet reusable       */
  void reg_recycle_slot(int32_t slot); /* files_mu held: back to free */

  RingCtx *pick_ring() {
    return rings[rr.fetch_add(1, std::memory_order_relaxed)
                 % n_rings].get();
  }
  RingCtx *ring_of_id(int64_t id) {
    if (id < 0) return nullptr;
    uint32_t ri = (uint32_t)(id & ((1 << STROM_RING_ID_BITS) - 1));
    return ri < n_rings ? rings[ri].get() : nullptr;
  }
  int64_t alloc_id(RingCtx *rc) {
    return (next_req.fetch_add(1, std::memory_order_relaxed)
            << STROM_RING_ID_BITS) | (int64_t)rc->idx;
  }
  bool file_copy(int fh, FileEnt *out) {
    std::lock_guard<std::mutex> g(files_mu);
    auto it = files.find(fh);
    if (it == files.end()) return false;
    *out = it->second;
    return true;
  }

  /* Assign a free staging buffer to r (1), park it on the global
   * deferral FIFO (0), or refuse because the engine is stopping (-1 —
   * the caller completes it -ECANCELED).  Never blocks.  The owning
   * ring's mutex must be held (pool_mu nests under it).  The stopping
   * re-check under pool_mu closes the race with destroy's cancel
   * sweep: either the sweep (also under pool_mu) sees our parked
   * request, or we see stopping — a request can never park AFTER the
   * sweep and wedge the drain. */
  int acquire_or_defer(Req *r) {
    std::lock_guard<std::mutex> g(pool_mu);
    if (!free_bufs.empty()) {
      r->buf_idx = free_bufs.back();
      free_bufs.pop_back();
      r->buf = buf_ptr(r->buf_idx);
      return 1;
    }
    if (stopping.load(std::memory_order_acquire)) return -1;
    defer_q.push_back(r);
    return 0;
  }

  void recycle_buffer(int buf_idx);   /* defined after RingCtx methods */

  std::atomic<uint64_t> st_direct{0}, st_fallback{0}, st_bounce{0},
      st_written{0}, st_sub{0}, st_comp{0}, st_fail{0}, st_retry{0},
      st_resident{0}, st_batches{0}, st_sysc_saved{0}, st_enters{0};
  bool probe_residency = true;   /* STROM_NO_RESIDENCY_PROBE disables */

  /* Fault injection BELOW Python (stress/chaos runs; see
   * nvme_strom_tpu/io/faults.py for the Python-level plan): read at
   * engine create from STROM_FAULT_READ_EIO_EVERY /
   * STROM_FAULT_READ_SHORT_EVERY / STROM_FAULT_READ_DELAY_MS.  All
   * zero (the default) keeps this path entirely off the hot loop. */
  uint64_t fault_eio_every = 0, fault_short_every = 0, fault_delay_ns = 0;
  std::atomic<uint64_t> fault_seq{0};
  /* Write-path mirror (STROM_FAULT_WRITE_*): the checkpoint/offload
   * durability story needs the native completion path to fail too —
   * EIO, ENOSPC, short write, completion delay. */
  uint64_t wfault_eio_every = 0, wfault_enospc_every = 0,
      wfault_short_every = 0, wfault_delay_ns = 0;
  std::atomic<uint64_t> wfault_seq{0};

  /* Applied at the read completion boundary (both backends funnel
   * through here right before complete(r)): a delay holds the
   * completion in flight — a latency straggler as the waiter sees it —
   * then every Nth read is failed with -EIO or halved (a short read
   * the caller must detect and recover). */
  void maybe_inject_read_fault(Req *r) {
    if (r->is_write ||
        !(fault_eio_every | fault_short_every | fault_delay_ns))
      return;
    uint64_t n = fault_seq.fetch_add(1, std::memory_order_relaxed) + 1;
    if (fault_delay_ns) {
      struct timespec ts = {
          (time_t)(fault_delay_ns / 1000000000ull),
          (long)(fault_delay_ns % 1000000000ull)};
      nanosleep(&ts, nullptr);
    }
    if (fault_eio_every && n % fault_eio_every == 0) {
      r->status = -EIO;
      r->done_len = 0;
      st_fail.fetch_add(1, std::memory_order_relaxed);
    } else if (fault_short_every && n % fault_short_every == 0 &&
               r->status == 0 && r->done_len > 1) {
      r->done_len /= 2;
    }
  }

  /* Write-completion injection (both backends funnel through here right
   * before complete(r) on the write branch): delay holds the completion
   * in flight, then every Nth write fails -EIO / -ENOSPC or reports
   * half its bytes written — the short-write resubmission case the
   * Python-level retry path must detect and finish. */
  void maybe_inject_write_fault(Req *r) {
    if (!r->is_write ||
        !(wfault_eio_every | wfault_enospc_every | wfault_short_every |
          wfault_delay_ns))
      return;
    uint64_t n = wfault_seq.fetch_add(1, std::memory_order_relaxed) + 1;
    if (wfault_delay_ns) {
      struct timespec ts = {
          (time_t)(wfault_delay_ns / 1000000000ull),
          (long)(wfault_delay_ns % 1000000000ull)};
      nanosleep(&ts, nullptr);
    }
    if (wfault_eio_every && n % wfault_eio_every == 0) {
      r->status = -EIO;
      r->done_len = 0;
      st_fail.fetch_add(1, std::memory_order_relaxed);
    } else if (wfault_enospc_every && n % wfault_enospc_every == 0) {
      r->status = -ENOSPC;
      r->done_len = 0;
      st_fail.fetch_add(1, std::memory_order_relaxed);
    } else if (wfault_short_every && n % wfault_short_every == 0 &&
               r->status == 0 && r->done_len > 1) {
      r->done_len /= 2;
    }
  }
  std::atomic<uint64_t> lat_read[STROM_LAT_BUCKETS] = {};
  std::atomic<uint64_t> lat_write[STROM_LAT_BUCKETS] = {};

  uint8_t *buf_ptr(int idx) { return pool + (uint64_t)idx * buf_cap; }

  /* Synchronous read with the full fallback ladder; used by the thread-pool
   * backend and by the reaper when an io_uring direct read needs rescue.
   * Fills req->status/done_len/was_fallback. Caller does NOT hold mu. */
  void read_sync(Req *r, const FileEnt &fe) {
    uint64_t avail = r->offset < (uint64_t)fe.size
                         ? std::min<uint64_t>(r->len, fe.size - r->offset)
                         : 0;
    if (avail == 0) { r->status = 0; r->done_len = 0; return; }
    uint64_t head = r->offset - r->a_off;
    if (fe.fd_direct >= 0 && r->direct) {
      uint64_t got = 0;
      bool ok = true;
      while (got < r->a_len) {
        ssize_t n = pread(fe.fd_direct, r->buf + got, r->a_len - got,
                          (off_t)(r->a_off + got));
        if (n < 0) { ok = false; break; }
        if (n == 0) break; /* EOF */
        got += (uint64_t)n;
      }
      if (ok && got >= head + avail) {
        r->status = 0;
        r->done_len = avail;
        st_direct.fetch_add(avail, std::memory_order_relaxed);
        return;
      }
      st_retry.fetch_add(1, std::memory_order_relaxed);
    }
    /* Buffered fallback: page cache in the middle -> host copy, counted. */
    uint64_t got = 0;
    while (got < avail) {
      ssize_t n = pread(fe.fd_buffered, r->buf + head + got, avail - got,
                        (off_t)(r->offset + got));
      if (n < 0) { r->status = -errno; st_fail.fetch_add(1); return; }
      if (n == 0) break;
      got += (uint64_t)n;
    }
    r->status = 0;
    r->done_len = got;
    r->was_fallback = true;
    st_fallback.fetch_add(got, std::memory_order_relaxed);
    st_bounce.fetch_add(got, std::memory_order_relaxed);
    if (r->planned_resident)
      st_resident.fetch_add(got, std::memory_order_relaxed);
  }

  void write_sync(Req *r, const FileEnt &fe) {
    const uint8_t *src = r->buf_idx >= 0 ? r->buf : (const uint8_t *)r->wsrc;
    int fd = (r->direct && fe.fd_direct >= 0) ? fe.fd_direct : fe.fd_buffered;
    uint64_t put = 0;
    while (put < r->len) {
      ssize_t n = pwrite(fd, src + put, r->len - put, (off_t)(r->offset + put));
      if (n < 0) {
        if (errno == EINVAL && fd == fe.fd_direct) {
          fd = fe.fd_buffered;
          r->was_fallback = true;
          st_retry.fetch_add(1, std::memory_order_relaxed);
          continue;
        }
        r->status = -errno;
        st_fail.fetch_add(1);
        return;
      }
      put += (uint64_t)n;
    }
    r->status = 0;
    r->done_len = put;
    if (!r->was_fallback && r->direct)
      st_written.fetch_add(put, std::memory_order_relaxed);
    else if (r->buf_idx < 0)
      /* Zero-copy attempt that fell back to buffered: the kernel's
       * page-cache copy is the bounce. (Staged writes already counted
       * their bounce at the memcpy into the staging buffer.) */
      st_bounce.fetch_add(put, std::memory_order_relaxed);
  }

};

void RingCtx::complete_locked(Req *r) {
  if (r->state == ReqState::kDone) return;  /* idempotent: a restart's
                                               cancel must not race a
                                               backend completion into
                                               double accounting */
  r->state = ReqState::kDone;
  r->t_complete = now_ns();
  if (r->status < 0 && r->status != -ECANCELED)
    rg_fail.fetch_add(1, std::memory_order_relaxed);
  if (r->status == 0) {
    /* Failures are counted in st_fail; bucketing their near-instant
     * "latency" would drag the p50/p99 gauges toward zero exactly when
     * the system is misbehaving. */
    uint64_t lat = r->t_complete - r->t_submit;
    int b = 63 - __builtin_clzll(lat | 1);
    (r->is_write ? eng->lat_write : eng->lat_read)[b].fetch_add(
        1, std::memory_order_relaxed);
  }
  /* release: pairs with the acquire load in strom_get_stats so an
   * observer that sees this completion also sees the corresponding
   * st_sub increment (which happens-before it via the request's
   * submit->complete chain). */
  eng->st_comp.fetch_add(1, std::memory_order_release);
  rg_comp.fetch_add(1, std::memory_order_release);
  cv_done.notify_all();
}

/* Hand a buffer-holding request to the backend. The ring mutex must be
 * held (files_mu is a leaf lock and may be taken under it).
 * Submissions never block: if the ring is jammed (practically impossible —
 * we drain the SQ on every enter) the request fails with -EBUSY.
 * ``flush_now = false`` defers the uring doorbell (vectored submit:
 * the caller flushes once for the whole batch). */
void RingCtx::dispatch_locked(Req *r, bool flush_now) {
  /* Failure-domain hooks: a restart window or an armed stall parks the
   * request (in order) instead of dispatching — it stays kInflight
   * with no backend I/O, exactly what a wedged submission queue looks
   * like to its waiter. */
  if (restarting) {
    r->parked = true;
    park_q.push_back(r);
    return;
  }
  if (stalled) {
    if (stall_seen >= stall_after) {
      r->parked = true;
      park_q.push_back(r);
      return;
    }
    stall_seen++;
  }
  r->dispatched = true;
  FileEnt fe;
  if (!eng->file_copy(r->fh, &fe)) {
    r->status = -EBADF;
    eng->st_fail.fetch_add(1, std::memory_order_relaxed);
    complete_locked(r);
    return;
  }
  if (use_uring) {
    int rc;
    /* A request holding a staging buffer targets registered memory:
     * use the fixed-buffer opcode so the kernel skips per-I/O pinning.
     * Every ring registered the WHOLE pool, so buf_index is global. */
    bool fixed = ring.fixed_bufs && r->buf_idx >= 0;
    uint16_t bidx = fixed ? (uint16_t)r->buf_idx : 0;
    /* Registered file: sqe->fd becomes the slot index and the kernel
     * skips the per-op fget — the hot-path half of "one doorbell". */
    int slot = r->direct ? fe.slot_direct : fe.slot_buffered;
    bool ff = ring.reg_files && slot >= 0;
    uint8_t sflags = ff ? kSqeFixedFile : 0;
    if (r->is_write) {
      const uint8_t *s = r->buf_idx >= 0 ? r->buf : (const uint8_t *)r->wsrc;
      int fd = r->direct ? fe.fd_direct : fe.fd_buffered;
      rc = ring.submit(fixed ? kOpWriteFixed : kOpWrite,
                       ff ? slot : fd,
                       r->offset, (void *)s, (uint32_t)r->len,
                       (uint64_t)r->id, bidx, flush_now, sflags);
    } else {
      int fd = r->direct ? fe.fd_direct : fe.fd_buffered;
      uint64_t off = r->direct ? r->a_off : r->offset;
      uint8_t *dst = r->direct ? r->buf : r->buf + (r->offset - r->a_off);
      uint32_t rlen = (uint32_t)(r->direct ? r->a_len : r->len);
      rc = ring.submit(fixed ? kOpReadFixed : kOpRead, ff ? slot : fd,
                       off, dst, rlen,
                       (uint64_t)r->id, bidx, flush_now, sflags);
    }
    if (rc != 0) {
      r->status = rc;
      eng->st_fail.fetch_add(1, std::memory_order_relaxed);
      complete_locked(r);
    }
    return;
  }
  work_q.push_back(r);
  if (sq_poll && poll_workers >= (int)work_q.size()) {
    /* SQPOLL analogue: enough pollers are awake to absorb the WHOLE
     * queue on their next poll tick — the wakeup doorbell is
     * unnecessary, which is the whole point of the mode.  Counted
     * exactly like the uring backend's elided io_uring_enter.  The
     * queue-size bound matters: unlike the kernel SQ thread (which
     * only consumes submissions), our pollers execute the full I/O —
     * eliding more wakeups than there are awake pollers would
     * serialize a burst behind one worker while the rest sleep. */
    eng->st_sysc_saved.fetch_add(1, std::memory_order_relaxed);
  } else {
    eng->st_enters.fetch_add(1, std::memory_order_relaxed);
    cv_work.notify_one();
  }
}

/* A staging buffer became free: hand it to the OLDEST deferred request
 * engine-wide (whatever its ring — this cross-ring handoff is the
 * deadlock-freedom guarantee a batch pinned to one ring relies on), or
 * return it to the global pool.  Called with NO locks held. */
void strom_engine::recycle_buffer(int buf_idx) {
  Req *next = nullptr;
  {
    std::lock_guard<std::mutex> g(pool_mu);
    if (defer_q.empty()) {
      free_bufs.push_back(buf_idx);
      return;
    }
    next = defer_q.front();
    defer_q.pop_front();
    next->buf_idx = buf_idx;
    next->buf = buf_ptr(buf_idx);
  }
  RingCtx *rc = next->rc;
  std::lock_guard<std::mutex> g(rc->mu);
  if (next->is_write) {
    /* Deferred bounce write: stage the caller bytes now. The wrapper
     * keeps the source alive until wait(). */
    memcpy(next->buf, next->wsrc, next->len);
    st_bounce.fetch_add(next->len, std::memory_order_relaxed);
  }
  rc->dispatch_locked(next);
}

void RingCtx::reaper_loop() {
  bool stop = false;
  while (!stop) {
    ring.reap([&](uint64_t ud, int32_t res) {
      if (ud == kShutdownUserData) { stop = true; return; }
      Req *r;
      {
        std::lock_guard<std::mutex> g(mu);
        auto it = reqs.find((int64_t)ud);
        if (it == reqs.end()) return;
        r = it->second;
      }
      FileEnt fe;
      if (!eng->file_copy(r->fh, &fe)) {
        r->status = -EBADF;
        complete(r);
        return;
      }
      if (r->is_write) {
        if (res >= 0 && (uint64_t)res == r->len) {
          r->status = 0;
          r->done_len = r->len;
          if (r->direct)
            eng->st_written.fetch_add(r->len, std::memory_order_relaxed);
          else if (r->buf_idx < 0)
            /* See write_sync: staged writes counted their bounce at the
             * staging memcpy already. */
            eng->st_bounce.fetch_add(r->len, std::memory_order_relaxed);
        } else {
          eng->st_retry.fetch_add(1, std::memory_order_relaxed);
          eng->write_sync(r, fe); /* rescue: finish/retry synchronously */
        }
        eng->maybe_inject_write_fault(r);
        complete(r);
        return;
      }
      /* Direct reads were submitted over the aligned span (head bytes of
       * slack precede the payload); buffered reads were submitted at the
       * exact offset and return at most `avail`. */
      uint64_t head = r->direct ? r->offset - r->a_off : 0;
      uint64_t avail = r->offset < (uint64_t)fe.size
                           ? std::min<uint64_t>(r->len, fe.size - r->offset)
                           : 0;
      if (res >= 0 && (uint64_t)res >= head + avail) {
        r->status = 0;
        r->done_len = avail;
        if (r->direct)
          eng->st_direct.fetch_add(avail, std::memory_order_relaxed);
        else {
          r->was_fallback = true;
          eng->st_fallback.fetch_add(avail, std::memory_order_relaxed);
          eng->st_bounce.fetch_add(avail, std::memory_order_relaxed);
          if (r->planned_resident)
            eng->st_resident.fetch_add(avail, std::memory_order_relaxed);
        }
      } else {
        /* Short read or error (EINVAL on tmpfs etc.): rescue path.
         * A rescued read is a RETRY, whatever the original plan —
         * clear planned_resident so its bytes never count as a
         * planned page-cache hit (header contract: resident is not
         * a rescue). */
        eng->st_retry.fetch_add(1, std::memory_order_relaxed);
        r->direct = false;
        r->planned_resident = false;
        eng->read_sync(r, fe);
        r->was_fallback = true;
      }
      eng->maybe_inject_read_fault(r);
      complete(r);
    });
  }
}

void RingCtx::worker_loop() {
  for (;;) {
    Req *r;
    {
      std::unique_lock<std::mutex> lk(mu);
      auto ready = [&] {
        return eng->stopping.load(std::memory_order_acquire) ||
               !work_q.empty();
      };
      if (sq_poll) {
        /* SQPOLL analogue: poll the queue in short ticks for up to
         * sq_idle_ns before sleeping.  While polling, this worker is
         * counted in poll_workers so dispatchers elide their wakeup
         * (the doorbell the mode removes); once the idle budget is
         * spent the worker sleeps indefinitely and the NEXT dispatch
         * pays one wakeup — exactly the kernel SQ thread's
         * NEED_WAKEUP handshake. */
        uint64_t idle_start = now_ns();
        while (!ready()) {
          poll_workers++;
          /* system-clock wait_until, NOT wait_for: libstdc++'s
           * steady-clock wait lands on pthread_cond_clockwait, which
           * gcc-10-era TSAN does not intercept — every poll tick would
           * then read as a phantom double-lock.  The poll cadence does
           * not care which clock measures 200 us. */
          cv_work.wait_until(lk, std::chrono::system_clock::now() +
                                     std::chrono::microseconds(200));
          poll_workers--;
          if (ready()) break;
          if (now_ns() - idle_start >= sq_idle_ns) {
            cv_work.wait(lk, ready);   /* asleep: doorbell required */
            break;
          }
        }
      } else {
        cv_work.wait(lk, ready);
      }
      if (work_q.empty()) return;  /* stopping, queue drained */
      r = work_q.front();
      work_q.pop_front();
    }
    FileEnt fe;
    if (!eng->file_copy(r->fh, &fe)) {
      r->status = -EBADF;
      complete(r);
      continue;
    }
    if (r->is_write)
      eng->write_sync(r, fe);
    else
      eng->read_sync(r, fe);
    eng->maybe_inject_read_fault(r);
    eng->maybe_inject_write_fault(r);
    complete(r);
  }
}

/* ------------------------- public C ABI ------------------------- */

extern "C" {

/* Registered-file slot budget per engine: big enough for every consumer
 * pattern in the repo (each open costs <= 2 slots: direct + buffered
 * fd); files past it simply submit by raw fd. */
#define STROM_REG_FILE_SLOTS 128

static strom_engine *engine_create_common(
    uint32_t n_rings, uint32_t queue_depth, uint32_t n_buffers,
    uint64_t buf_bytes, uint32_t alignment, int use_io_uring,
    int lock_buffers, void *prealloc, uint64_t prealloc_bytes) {
  if (!n_rings || n_rings > STROM_MAX_RINGS || !queue_depth || !n_buffers ||
      !buf_bytes || !alignment || (alignment & (alignment - 1))) {
    errno = EINVAL;
    return nullptr;
  }
  auto *e = new strom_engine();
  e->n_rings = n_rings;
  e->queue_depth = queue_depth;
  e->n_buffers = n_buffers;
  e->alignment = alignment;
  e->buf_bytes = buf_bytes;
  e->buf_cap = align_up(buf_bytes, alignment) + 2 * (uint64_t)alignment;
  /* ONE formula, shared with the public helper: a prealloc caller's
   * computed carve size must never drift from the engine's own check */
  e->pool_sz = (size_t)strom_engine_pool_bytes(n_rings, n_buffers,
                                               buf_bytes, alignment);
  if (prealloc != nullptr) {
    /* Arena carve (io/arena.py): the caller owns (and outlives) the
     * mapping; the engine stages into it but never unmaps it. */
    if (prealloc_bytes < e->pool_sz) {
      delete e;
      errno = EINVAL;
      return nullptr;
    }
    e->pool = (uint8_t *)prealloc;
    e->owns_pool = false;
  } else {
    e->pool = (uint8_t *)mmap(nullptr, e->pool_sz, PROT_READ | PROT_WRITE,
                              MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (e->pool == MAP_FAILED) { delete e; return nullptr; }
  }
  /* Pin the pool — the MAP_GPU_MEMORY analogue: the reference pins BAR1
   * pages so DMA targets never move (SURVEY.md §3.2); we pin staging pages
   * so neither NVMe DMA nor the TPU transfer hits a fault. Soft-fail.
   * (A prealloc'd pool is re-mlocked here harmlessly: destroy skips the
   * munmap, so the arena's lock outlives the engine either way.) */
  if (lock_buffers) e->locked = mlock(e->pool, e->pool_sz) == 0;
  e->probe_residency = getenv("STROM_NO_RESIDENCY_PROBE") == nullptr;
  {
    /* Chaos knobs (tests/stress only; all default off — see
     * maybe_inject_read_fault). */
    auto env_u64 = [](const char *name) -> uint64_t {
      const char *v = getenv(name);
      return v ? strtoull(v, nullptr, 10) : 0;
    };
    e->fault_eio_every = env_u64("STROM_FAULT_READ_EIO_EVERY");
    e->fault_short_every = env_u64("STROM_FAULT_READ_SHORT_EVERY");
    e->fault_delay_ns = env_u64("STROM_FAULT_READ_DELAY_MS") * 1000000ull;
    e->wfault_eio_every = env_u64("STROM_FAULT_WRITE_EIO_EVERY");
    e->wfault_enospc_every = env_u64("STROM_FAULT_WRITE_ENOSPC_EVERY");
    e->wfault_short_every = env_u64("STROM_FAULT_WRITE_SHORT_EVERY");
    e->wfault_delay_ns = env_u64("STROM_FAULT_WRITE_DELAY_MS") * 1000000ull;
  }
  {
    /* Zero-copy submission modes (PR 12; defaults: registered files
     * on — they soft-fail harmlessly — SQPOLL opt-in: the poller burns
     * a core while idle, a deliberate spend). */
    const char *v = getenv("STROM_REG_FILES");
    e->reg_files_enabled = !(v && v[0] == '0' && v[1] == '\0');
    v = getenv("STROM_SQPOLL");
    e->sqpoll_enabled = v && v[0] == '1' && v[1] == '\0';
    if (const char *ims = getenv("STROM_SQPOLL_IDLE_MS")) {
      uint64_t ms = strtoull(ims, nullptr, 10);
      if (ms > 0 && ms <= 10000) e->sqpoll_idle_ms = (uint32_t)ms;
    }
  }
  e->reg_fds.assign(STROM_REG_FILE_SLOTS, -1);
  for (int s = STROM_REG_FILE_SLOTS - 1; s >= 0; s--)
    e->reg_free.push_back((uint32_t)s);
  for (int i = (int)(n_buffers * n_rings) - 1; i >= 0; i--)
    e->free_bufs.push_back(i);
  /* Ring-stall injection (chaos; default off): the named ring parks
   * its dispatches after the first N — the deterministic wedged-ring
   * drive for the supervision layer (io/health.py). */
  const char *stall_ring_env = getenv("STROM_FAULT_RING_STALL_RING");
  int64_t stall_ring = stall_ring_env ? strtoll(stall_ring_env, nullptr, 10)
                                      : -1;
  uint64_t stall_after = 0;
  if (const char *v = getenv("STROM_FAULT_RING_STALL_AFTER"))
    stall_after = strtoull(v, nullptr, 10);
  for (uint32_t ri = 0; ri < n_rings; ri++) {
    auto rcp = std::unique_ptr<RingCtx>(new RingCtx());
    RingCtx *rc = rcp.get();
    rc->eng = e;
    rc->idx = ri;
    if (stall_ring >= 0 && (uint32_t)stall_ring == ri) {
      rc->stalled = true;
      rc->stall_after = stall_after;
    }
    rc->ring.want_sqpoll = e->sqpoll_enabled;
    rc->ring.sqpoll_idle_ms = e->sqpoll_idle_ms;
    rc->ring.c_enters = &e->st_enters;
    rc->ring.c_elided = &e->st_sysc_saved;
    if (use_io_uring && rc->ring.init(queue_depth * 2)) {
      rc->use_uring = true;
      /* Each ring registers the WHOLE pool with its uring fd: buffers
       * are fungible across rings (deadlock freedom — see pool_mu). */
      rc->ring.try_register(e->pool, e->buf_cap, n_buffers * n_rings);
      if (e->reg_files_enabled)
        rc->ring.try_register_files(e->reg_fds.data(),
                                    STROM_REG_FILE_SLOTS);
      rc->reaper = std::thread([rc] { rc->reaper_loop(); });
    } else {
      rc->sq_poll = e->sqpoll_enabled;
      rc->sq_idle_ns = (uint64_t)e->sqpoll_idle_ms * 1000000ull;
      uint32_t nw = queue_depth < 32 ? queue_depth : 32;
      for (uint32_t i = 0; i < nw; i++)
        rc->workers.emplace_back([rc] { rc->worker_loop(); });
    }
    e->rings.push_back(std::move(rcp));
  }
  return e;
}

strom_engine *strom_engine_create_rings(uint32_t n_rings,
                                        uint32_t queue_depth,
                                        uint32_t n_buffers,
                                        uint64_t buf_bytes,
                                        uint32_t alignment,
                                        int use_io_uring, int lock_buffers) {
  return engine_create_common(n_rings, queue_depth, n_buffers, buf_bytes,
                              alignment, use_io_uring, lock_buffers,
                              nullptr, 0);
}

strom_engine *strom_engine_create_prealloc(uint32_t n_rings,
                                           uint32_t queue_depth,
                                           uint32_t n_buffers,
                                           uint64_t buf_bytes,
                                           uint32_t alignment,
                                           int use_io_uring,
                                           int lock_buffers,
                                           void *pool,
                                           uint64_t pool_bytes) {
  if (!pool) { errno = EINVAL; return nullptr; }
  return engine_create_common(n_rings, queue_depth, n_buffers, buf_bytes,
                              alignment, use_io_uring, lock_buffers,
                              pool, pool_bytes);
}

uint64_t strom_engine_pool_bytes(uint32_t n_rings, uint32_t n_buffers,
                                 uint64_t buf_bytes, uint32_t alignment) {
  if (!n_rings || n_rings > STROM_MAX_RINGS || !n_buffers || !buf_bytes ||
      !alignment || (alignment & (alignment - 1)))
    return 0;
  uint64_t cap = align_up(buf_bytes, alignment) + 2 * (uint64_t)alignment;
  return cap * n_buffers * n_rings;
}

strom_engine *strom_engine_create(uint32_t queue_depth, uint32_t n_buffers,
                                  uint64_t buf_bytes, uint32_t alignment,
                                  int use_io_uring, int lock_buffers) {
  return strom_engine_create_rings(1, queue_depth, n_buffers, buf_bytes,
                                   alignment, use_io_uring, lock_buffers);
}

/* ---- unified pinned arena (io/arena.py) ---- */

void *strom_arena_create(uint64_t bytes) {
  if (bytes == 0) { errno = EINVAL; return NULL; }
  /* NORESERVE: the arena is a cheap VIRTUAL reservation — pages commit
   * (and pin, via strom_arena_lock) per CARVE, so a generously sized
   * arena costs nothing until consumers actually stage into it. */
  void *base = mmap(NULL, bytes, PROT_READ | PROT_WRITE,
                    MAP_PRIVATE | MAP_ANONYMOUS | MAP_NORESERVE, -1, 0);
  if (base == MAP_FAILED) {
    base = mmap(NULL, bytes, PROT_READ | PROT_WRITE,
                MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (base == MAP_FAILED) return NULL;
  }
  return base;
}

void strom_arena_destroy(void *base, uint64_t bytes) {
  if (base && bytes) munmap(base, bytes);
}

int strom_arena_lock(void *base, uint64_t bytes) {
  if (!base || !bytes) return -EINVAL;
  return mlock(base, bytes) == 0 ? 0 : -errno;
}

void strom_engine_destroy(strom_engine *e) {
  if (!e) return;
  e->stopping.store(true, std::memory_order_release);
  /* Flush any in-flight restart before tearing rings down (bounded
   * wait: a restart's drain is bounded by its timeout).  Acquire-and-
   * release: `stopping` is already visible, and strom_ring_restart
   * re-checks it under this mutex, so no NEW restart can start — and
   * the guard must not live across the `delete e` below. */
  { std::lock_guard<std::mutex> restart_guard(e->restart_mu); }
  for (auto &rcp : e->rings) {
    /* Parked (stalled / restart-window) requests never reached a
     * backend: cancel them so the per-ring drain below cannot wedge
     * waiting for completions that will never arrive. */
    RingCtx *rc = rcp.get();
    std::lock_guard<std::mutex> g(rc->mu);
    while (!rc->park_q.empty()) {
      Req *r = rc->park_q.front();
      rc->park_q.pop_front();
      r->parked = false;
      r->status = -ECANCELED;
      r->done_len = 0;
      rc->complete_locked(r);
    }
  }
  {
    /* Cancel the global deferral FIFO first: a deferred request's ring
     * drain below would otherwise wait forever for a buffer that no
     * releaser will recycle once callers stop. */
    std::deque<Req *> cancelled;
    {
      std::lock_guard<std::mutex> g(e->pool_mu);
      cancelled.swap(e->defer_q);
    }
    for (Req *r : cancelled) {
      RingCtx *rc = r->rc;
      std::lock_guard<std::mutex> g(rc->mu);
      r->status = -ECANCELED;
      rc->complete_locked(r);
    }
  }
  for (auto &rcp : e->rings) {
    RingCtx *rc = rcp.get();
    std::unique_lock<std::mutex> lk(rc->mu);
    rc->cv_work.notify_all();
    /* Drain: every in-flight request's DMA targets the staging pool — the
     * pool cannot be unmapped until the kernel is done with it. */
    rc->cv_done.wait(lk, [&] {
      for (auto &kv : rc->reqs)
        if (kv.second->state != ReqState::kDone) return false;
      return true;
    });
  }
  for (auto &rcp : e->rings) {
    RingCtx *rc = rcp.get();
    if (rc->use_uring) {
      {
        std::lock_guard<std::mutex> g(rc->mu);
        rc->ring.submit(kOpNop, -1, 0, nullptr, 0, kShutdownUserData);
      }
      if (rc->reaper.joinable()) rc->reaper.join();
      rc->ring.teardown();
    }
    for (auto &w : rc->workers)
      if (w.joinable()) w.join();
  }
  for (auto &kv : e->files) {
    if (kv.second.fd_direct >= 0) close(kv.second.fd_direct);
    if (kv.second.fd_buffered >= 0) close(kv.second.fd_buffered);
  }
  for (auto &rcp : e->rings)
    for (auto &kv : rcp->reqs) delete kv.second;
  /* An arena-carved pool belongs to the caller (io/arena.py recycles
   * the carve); unmapping it here would yank live cache lines and DMA
   * slabs sharing the arena. */
  if (e->pool && e->owns_pool) munmap(e->pool, e->pool_sz);
  delete e;
}

/* ---- registered-file slot table (files_mu held by callers) ---- */

int32_t strom_engine::reg_alloc_slot(int fd) {
  if (fd < 0 || reg_free.empty()) return -1;
  uint32_t slot = reg_free.back();
  reg_free.pop_back();
  reg_fds[slot] = fd;
  return (int32_t)slot;
}

void strom_engine::reg_clear_slot(int32_t slot) {
  if (slot >= 0) reg_fds[slot] = -1;
}

void strom_engine::reg_recycle_slot(int32_t slot) {
  /* Only AFTER the rings' slot entries were updated to -1: recycling
   * first would let a concurrent open re-allocate the slot and
   * register a fresh fd that our in-flight -1 update then clobbers. */
  if (slot >= 0) reg_free.push_back((uint32_t)slot);
}

void strom_engine::reg_update_all(uint32_t slot, int32_t newfd) {
  for (auto &rcp : rings) {
    RingCtx *rc = rcp.get();
    if (rc->use_uring && rc->ring.reg_files) {
      if (!rc->ring.update_file(slot, newfd))
        rc->ring.reg_files = false;   /* stale slots are never risked */
    }
  }
}

int strom_ring_count(strom_engine *e) { return (int)e->n_rings; }

int64_t strom_ring_inflight(strom_engine *e, uint32_t ring) {
  if (ring >= e->n_rings) return -EINVAL;
  RingCtx *rc = e->rings[ring].get();
  /* completed first: see strom_get_ring_info */
  uint64_t comp = rc->rg_comp.load(std::memory_order_acquire);
  uint64_t sub = rc->rg_sub.load(std::memory_order_relaxed);
  return sub > comp ? (int64_t)(sub - comp) : 0;
}

int strom_get_ring_info(strom_engine *e, uint32_t ring,
                        strom_ring_info *out) {
  if (ring >= e->n_rings) return -EINVAL;
  RingCtx *rc = e->rings[ring].get();
  /* completed BEFORE submitted: any completion implies visibility of its
   * own submission, so the snapshot's depth (sub - comp) is never
   * negative. */
  uint64_t comp = rc->rg_comp.load(std::memory_order_acquire);
  uint64_t sub = rc->rg_sub.load(std::memory_order_relaxed);
  out->ring_id = ring;
  out->n_buffers = e->n_buffers * e->n_rings;  /* pool is global */
  out->submitted = sub;
  out->completed = comp;
  out->inflight_io = (uint32_t)(sub > comp ? sub - comp : 0);
  out->backend_uring = rc->use_uring ? 1 : 0;
  out->failed = rc->rg_fail.load(std::memory_order_relaxed);
  out->restarts = rc->rg_restarts.load(std::memory_order_relaxed);
  {
    /* Health walk under the ring mutex (request maps are queue-depth
     * sized — this is a stat poll, not the dispatch hot path): parked
     * backlog plus the age of the oldest request a backend owes a
     * completion for.  Deferred requests are excluded from the age —
     * pool pressure is not a ring stall. */
    std::lock_guard<std::mutex> g(rc->mu);
    out->parked = (uint32_t)rc->park_q.size();
    out->stalled = rc->stalled ? 1 : 0;
    /* zero-copy submission state (PR 12), read under the ring mutex (a
     * hot restart rewrites these during its rebuild): a silently-
     * unregistered pool or slot table must be VISIBLE, not just slow */
    out->fixed_bufs = rc->use_uring && rc->ring.fixed_bufs ? 1 : 0;
    out->reg_files = rc->use_uring &&
        rc->ring.reg_files.load(std::memory_order_relaxed) ? 1 : 0;
    out->sqpoll = (rc->use_uring ? rc->ring.sqpoll : rc->sq_poll) ? 1 : 0;
    uint64_t oldest = 0;
    for (auto &kv : rc->reqs) {
      Req *r = kv.second;
      if (r->state == ReqState::kDone || !(r->dispatched || r->parked))
        continue;
      if (oldest == 0 || r->t_submit < oldest) oldest = r->t_submit;
    }
    out->oldest_inflight_ns = oldest ? now_ns() - oldest : 0;
  }
  {
    std::lock_guard<std::mutex> g(e->pool_mu);
    out->free_buffers = (uint32_t)e->free_bufs.size();
    uint32_t d = 0;
    for (Req *r : e->defer_q)
      if (r->rc == rc) d++;
    out->deferred = d;
  }
  return 0;
}

int strom_set_ring_stall(strom_engine *e, uint32_t ring, int on) {
  if (ring >= e->n_rings) return -EINVAL;
  RingCtx *rc = e->rings[ring].get();
  std::lock_guard<std::mutex> g(rc->mu);
  rc->stalled = on != 0;
  rc->stall_after = 0;
  rc->stall_seen = 0;
  if (!rc->stalled && !rc->restarting) {
    /* Disarm = the wedge healed on its own: dispatch the parked
     * backlog in order (waiters just saw one longer wait). */
    while (!rc->park_q.empty()) {
      Req *r = rc->park_q.front();
      rc->park_q.pop_front();
      r->parked = false;
      rc->dispatch_locked(r);
    }
  }
  return 0;
}

int64_t strom_ring_restart(strom_engine *e, uint32_t ring,
                           uint64_t drain_timeout_ns) {
  if (ring >= e->n_rings) return -EINVAL;
  if (e->stopping.load(std::memory_order_acquire)) return -ECANCELED;
  /* One restart at a time engine-wide, and never concurrent with
   * destroy (restart_mu is outermost; the drain below is bounded, so
   * a destroy blocked on it waits at most drain_timeout_ns). */
  std::unique_lock<std::mutex> restart_guard(e->restart_mu,
                                             std::try_to_lock);
  if (!restart_guard.owns_lock()) return -EBUSY;
  if (e->stopping.load(std::memory_order_acquire)) return -ECANCELED;
  RingCtx *rc = e->rings[ring].get();
  int64_t cancelled = 0;
  bool drained;
  {
    std::unique_lock<std::mutex> lk(rc->mu);
    rc->restarting = true;  /* new dispatches park until the rebuild */
    /* requests parked BEFORE this restart are the wedged backlog the
     * restart exists to requeue; anything parking during the window
     * (appended behind them) is fresh traffic that must DISPATCH
     * after the rebuild, never cancel */
    size_t pre_parked = rc->park_q.size();
    /* 1) bounded drain of I/O a backend actually owns (the predicate
     * ignores parked requests — no backend ever saw those).  An
     * un-completable request cannot be cancelled from here (its
     * staging buffer is a live DMA target): on timeout the restart
     * ABORTS with the ring truly as it was — parked requests stay
     * parked, nothing was cancelled — and the caller falls back to
     * degraded buffered reads. */
    auto quiesced = [&] {
      for (auto &kv : rc->reqs) {
        Req *r = kv.second;
        if (r->state != ReqState::kDone && r->dispatched) return false;
      }
      return true;
    };
    drained = rc->cv_done.wait_for(
        lk, std::chrono::nanoseconds(drain_timeout_ns), quiesced);
    if (!drained) {
      rc->restarting = false;
      /* requests parked during the window resume on the (still-sick)
       * backend — status quo ante; the supervisor keeps the breaker
       * open and routes around the ring.  Drain via a LOCAL queue:
       * with stall injection still armed, dispatch_locked re-parks
       * each request into rc->park_q — draining that same queue
       * in place would spin forever under both mutexes. */
      std::deque<Req *> resume;
      resume.swap(rc->park_q);
      while (!resume.empty()) {
        Req *r = resume.front();
        resume.pop_front();
        r->parked = false;
        rc->dispatch_locked(r);
      }
      return -ETIMEDOUT;
    }
    /* 2) the restart is now committed: cancel the stall-parked
     * backlog.  No backend ever saw these, so their buffers are clean
     * — the waiter's retry (ResilientRead) resubmits them, and the
     * engine's healthy-ring routing lands the resubmission elsewhere:
     * the requeue path.  Cancelling only AFTER the drain succeeded
     * keeps the return value exact (a timed-out restart requeued
     * nothing) and the abort contract honest. */
    while (pre_parked-- > 0 && !rc->park_q.empty()) {
      Req *r = rc->park_q.front();
      rc->park_q.pop_front();
      r->parked = false;
      r->status = -ECANCELED;
      r->done_len = 0;
      rc->complete_locked(r);
      cancelled++;
    }
  }
  /* 3) rebuild the uring outside the ring mutex (the nop handshake
   * below needs the reaper to keep consuming).  The quiesced ring has
   * nothing in flight, so the teardown/re-init races nobody. */
  if (rc->use_uring) {
    {
      std::lock_guard<std::mutex> g(rc->mu);
      rc->ring.submit(kOpNop, -1, 0, nullptr, 0, kShutdownUserData);
    }
    if (rc->reaper.joinable()) rc->reaper.join();
    /* In-place rebuild under the ring mutex (strom_get_pool_info reads
     * ring.fixed_bufs under it): the quiesced ring has no in-flight
     * I/O and the reaper is joined, so nobody else touches the Uring. */
    std::lock_guard<std::mutex> g(rc->mu);
    rc->ring.teardown();
    rc->ring.unsubmitted.store(0, std::memory_order_relaxed);
    rc->ring.fixed_bufs = false;
    if (rc->ring.init(e->queue_depth * 2)) {
      rc->ring.try_register(e->pool, e->buf_cap,
                            e->n_buffers * e->n_rings);
      if (e->reg_files_enabled) {
        /* Fresh uring, fresh registrations: re-register the CURRENT
         * slot table (files_mu is a leaf lock under the ring mutex) so
         * files opened before the restart keep their fixed slots.
         * init() preserved want_sqpoll, so SQPOLL re-arms identically.
         */
        std::lock_guard<std::mutex> fg(e->files_mu);
        rc->ring.try_register_files(e->reg_fds.data(),
                                    STROM_REG_FILE_SLOTS);
      }
      rc->reaper = std::thread([rc] { rc->reaper_loop(); });
    } else {
      /* Rebuild refused (fd limits, kernel state): fall back to the
       * worker-pool backend so the ring keeps serving. */
      rc->use_uring = false;
      rc->sq_poll = e->sqpoll_enabled;
      rc->sq_idle_ns = (uint64_t)e->sqpoll_idle_ms * 1000000ull;
      uint32_t nw = e->queue_depth < 32 ? e->queue_depth : 32;
      for (uint32_t i = 0; i < nw; i++)
        rc->workers.emplace_back([rc] { rc->worker_loop(); });
    }
  }
  {
    /* 4) reopen: disarm stall injection (the restart heals the wedge —
     * that is its contract) and dispatch requests parked during the
     * window, in order. */
    std::lock_guard<std::mutex> g(rc->mu);
    rc->stalled = false;
    rc->stall_seen = 0;
    rc->restarting = false;
    while (!rc->park_q.empty()) {
      Req *r = rc->park_q.front();
      rc->park_q.pop_front();
      r->parked = false;
      rc->dispatch_locked(r);
    }
    rc->rg_restarts.fetch_add(1, std::memory_order_relaxed);
  }
  return cancelled;
}

int64_t strom_read_buffered(strom_engine *e, int fh, uint64_t offset,
                            uint64_t len, void *dst) {
  FileEnt fe;
  if (!e->file_copy(fh, &fe)) return -EBADF;
  uint64_t got = 0;
  while (got < len) {
    ssize_t n = pread(fe.fd_buffered, (uint8_t *)dst + got, len - got,
                      (off_t)(offset + got));
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (n == 0) break; /* EOF */
    got += (uint64_t)n;
  }
  /* Honest accounting: this payload rode the page cache and was host-
   * copied into the caller's buffer — fallback + bounce, exactly like
   * the engine's own buffered rescue path. */
  e->st_fallback.fetch_add(got, std::memory_order_relaxed);
  e->st_bounce.fetch_add(got, std::memory_order_relaxed);
  return (int64_t)got;
}

int strom_check_file(const char *path, strom_file_info *out) {
  memset(out, 0, sizeof(*out));
  struct stat st;
  if (stat(path, &st) != 0) return -errno;
  out->size = (int64_t)st.st_size;
  out->block_size = (int32_t)(st.st_blksize ? st.st_blksize : 4096);
  struct statfs sfs;
  if (statfs(path, &sfs) == 0) out->fs_magic = (uint64_t)sfs.f_type;
  int fd = open(path, O_RDONLY | O_DIRECT);
  if (fd >= 0) {
    /* Probe an actual aligned read — some filesystems accept the open but
     * fail reads (the reference probes fs type + blockdev instead,
     * SURVEY.md §3.3). */
    void *p = nullptr;
    if (posix_memalign(&p, 4096, 4096) == 0) {
      ssize_t n = pread(fd, p, 4096, 0);
      out->supports_direct = (n >= 0) ? 1 : 0;
      free(p);
    }
    close(fd);
  }
  return 0;
}

/* ---- backing-device topology (CHECK_FILE's blockdev half, §3.3) ---- */

static int sysfs_read_line(const char *path, char *buf, size_t n) {
  FILE *f = fopen(path, "r");
  if (!f) return -1;
  char *got = fgets(buf, (int)n, f);
  fclose(f);
  if (!got) return -1;
  buf[strcspn(buf, "\n")] = 0;
  return 0;
}

/* Resolve a sysfs block-device link (/sys/dev/block/M:m or
 * /sys/class/block/<name>) to the WHOLE-DISK name: partitions step up to
 * their parent directory, mirroring the reference's partition->blockdev
 * walk in the CHECK_FILE handler (SURVEY.md §2 "File eligibility"). */
static int whole_disk_name(const char *sys_link, char *name, size_t n) {
  char real[PATH_MAX];
  if (!realpath(sys_link, real)) return -1;
  char probe[PATH_MAX + 16];
  snprintf(probe, sizeof(probe), "%s/partition", real);
  if (access(probe, F_OK) == 0) {
    char *slash = strrchr(real, '/');
    if (!slash) return -1;
    *slash = '\0';
  }
  const char *base = strrchr(real, '/');
  if (!base || !base[1]) return -1;
  snprintf(name, n, "%s", base + 1);
  return 0;
}

static int name_is_nvme(const char *name) {
  return strncmp(name, "nvme", 4) == 0;
}

int strom_resolve_device(const char *path, strom_device_info *out) {
  memset(out, 0, sizeof(*out));
  out->raid_level = -1;
  out->rotational = -1;
  struct stat st;
  if (stat(path, &st) != 0) return -errno;
  char link[96];
  snprintf(link, sizeof(link), "/sys/dev/block/%u:%u",
           major(st.st_dev), minor(st.st_dev));
  if (whole_disk_name(link, out->device, sizeof(out->device)) != 0)
    return 0; /* overlay/tmpfs/network fs: no visible backing blockdev */

  char p[PATH_MAX];
  char buf[64];
  snprintf(p, sizeof(p), "/sys/block/%s/queue/rotational", out->device);
  if (sysfs_read_line(p, buf, sizeof(buf)) == 0)
    out->rotational = atoi(buf);
  out->is_nvme = name_is_nvme(out->device);

  snprintf(p, sizeof(p), "/sys/block/%s/md", out->device);
  if (access(p, F_OK) != 0) {
    out->nvme_backed = out->is_nvme;
    return 0;
  }
  /* md array: level + member walk (reference: "md-raid0 stripe
   * resolution", SURVEY.md §2/§3.1). */
  out->is_raid = 1;
  snprintf(p, sizeof(p), "/sys/block/%s/md/level", out->device);
  if (sysfs_read_line(p, buf, sizeof(buf)) == 0 &&
      strncmp(buf, "raid", 4) == 0)
    out->raid_level = atoi(buf + 4);
  snprintf(p, sizeof(p), "/sys/block/%s/slaves", out->device);
  DIR *d = opendir(p);
  int all_nvme = 1;
  if (d) {
    struct dirent *de;
    /* Scan EVERY member for the all-NVMe verdict; members[] records only
     * the first STROM_MAX_RAID_MEMBERS names — ordered by md SLOT, not
     * readdir order: raid0 chunk k lives on slot (k mod n), so stripe
     * attribution (strom_stripe_attr) is only meaningful against the
     * slot order.  /sys/block/mdX/md/dev-<name>/slot holds it; members
     * with no readable slot (spares, legacy sysfs) keep scan order
     * after the slotted ones. */
    int slots[STROM_MAX_RAID_MEMBERS];
    while ((de = readdir(d)) != nullptr) {
      if (de->d_name[0] == '.') continue;
      char slink[PATH_MAX];
      char mname[64];
      snprintf(slink, sizeof(slink), "/sys/class/block/%.200s", de->d_name);
      if (whole_disk_name(slink, mname, sizeof(mname)) != 0)
        snprintf(mname, sizeof(mname), "%.63s", de->d_name);
      if (out->n_members < STROM_MAX_RAID_MEMBERS) {
        int slot = INT32_MAX;  /* unknown slots sort last, stably */
        char sp[PATH_MAX];
        snprintf(sp, sizeof(sp), "/sys/block/%s/md/dev-%.200s/slot",
                 out->device, de->d_name);
        FILE *sf = fopen(sp, "r");
        if (sf) {
          if (fscanf(sf, "%d", &slot) != 1) slot = INT32_MAX;
          fclose(sf);
        }
        int i = out->n_members;
        while (i > 0 && slots[i - 1] > slot) {  /* insertion sort */
          slots[i] = slots[i - 1];
          memcpy(out->members[i], out->members[i - 1],
                 sizeof(out->members[0]));
          i--;
        }
        slots[i] = slot;
        memcpy(out->members[i], mname, sizeof(mname));
      }
      out->n_members++;
      if (!name_is_nvme(mname)) all_nvme = 0;
    }
    closedir(d);
  }
  out->nvme_backed =
      (out->raid_level == 0 && out->n_members > 0 && all_nvme) ? 1 : 0;
  return 0;
}

int strom_file_extents(const char *path, strom_extent *out, uint32_t max) {
  if (max == 0) return 0;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  struct stat st;
  if (fstat(fd, &st) != 0) { int e = -errno; close(fd); return e; }
  if (st.st_size == 0) { close(fd); return 0; }

#ifdef FS_IOC_FIEMAP
  size_t sz = sizeof(struct fiemap) + (size_t)max * sizeof(struct fiemap_extent);
  struct fiemap *fm = (struct fiemap *)calloc(1, sz);
  if (!fm) { close(fd); return -ENOMEM; }
  /* Batched walk with an advancing window: a map that does not fit in
   * `max` entries is an error (-E2BIG), never a silent truncation — the
   * caller retries with a bigger buffer.  The reference's extent walk has
   * the same never-drop-the-tail property (it chunks the whole range,
   * SURVEY.md §3.1). */
  uint32_t count = 0;
  uint64_t start = 0;
  bool supported = true;
  int err = 0;
  while (true) {
    memset(fm, 0, sizeof(struct fiemap));
    fm->fm_start = start;
    fm->fm_length = (uint64_t)st.st_size - start;
    fm->fm_flags = FIEMAP_FLAG_SYNC;
    fm->fm_extent_count = max - count;
    if (ioctl(fd, FS_IOC_FIEMAP, fm) != 0) {
      if (errno == ENOTTY || errno == EOPNOTSUPP) {
        supported = false; /* fs has no FIEMAP: synthetic fallback below */
      } else {
        err = -errno;      /* real I/O error: propagate, do not mask */
      }
      break;
    }
    uint32_t n = fm->fm_mapped_extents;
    if (n == 0) break; /* sparse tail hole — map complete */
    if (n > max - count) n = max - count;
    bool last = false;
    for (uint32_t i = 0; i < n; i++) {
      out[count + i].logical = fm->fm_extents[i].fe_logical;
      out[count + i].physical = fm->fm_extents[i].fe_physical;
      out[count + i].length = fm->fm_extents[i].fe_length;
      out[count + i].flags = fm->fm_extents[i].fe_flags;
      out[count + i].pad = 0;
      if (fm->fm_extents[i].fe_flags & FIEMAP_EXTENT_LAST) last = true;
    }
    count += n;
    start = out[count - 1].logical + out[count - 1].length;
    if (last || start >= (uint64_t)st.st_size) break;
    if (count == max) { err = -E2BIG; break; } /* more extents than room */
  }
  free(fm);
  if (err != 0) { close(fd); return err; }
  if (supported) { close(fd); return (int)count; }
#endif
  /* No FIEMAP (tmpfs/overlay/proc): one synthetic whole-file extent. */
  out[0].logical = 0;
  out[0].physical = 0;
  out[0].length = (uint64_t)st.st_size;
  out[0].flags = STROM_EXTENT_SYNTHETIC;
  out[0].pad = 0;
  close(fd);
  return 1;
}

void strom_stripe_attr(uint64_t phys_off, uint64_t len, uint64_t chunk,
                       uint32_t n_members, uint64_t *out_bytes) {
  if (len == 0 || n_members == 0 || chunk == 0) return;
  if (n_members == 1) { out_bytes[0] += len; return; }
  const uint64_t period = chunk * (uint64_t)n_members;
  /* whole stripe periods cover every member equally */
  const uint64_t full = len / period;
  if (full) {
    for (uint32_t m = 0; m < n_members; m++) out_bytes[m] += full * chunk;
  }
  /* remainder: walk at most n_members+1 chunk fragments */
  uint64_t off = phys_off + full * period;
  uint64_t left = len % period;
  while (left) {
    const uint64_t in_chunk = chunk - (off % chunk);
    const uint64_t take = left < in_chunk ? left : in_chunk;
    out_bytes[(off / chunk) % n_members] += take;
    off += take;
    left -= take;
  }
}

void strom_get_pool_info(strom_engine *e, strom_pool_info *out) {
  /* Global pool + per-ring request maps; per-ring occupancy is
   * strom_get_ring_info. */
  uint32_t freeb = 0, infl = 0, def = 0;
  int fixed = e->rings.empty() ? 0 : 1;
  {
    std::lock_guard<std::mutex> g(e->pool_mu);
    freeb = (uint32_t)e->free_bufs.size();
    def = (uint32_t)e->defer_q.size();
  }
  for (auto &rcp : e->rings) {
    RingCtx *rc = rcp.get();
    std::lock_guard<std::mutex> g(rc->mu);
    infl += (uint32_t)rc->reqs.size();
    if (!rc->ring.fixed_bufs) fixed = 0;
  }
  out->n_buffers = e->n_buffers * e->n_rings;
  out->free_buffers = freeb;
  out->buf_bytes = e->buf_bytes;
  out->pool_bytes = (uint64_t)e->pool_sz;
  out->locked = e->locked ? 1 : 0;
  out->queue_depth = (int32_t)(e->queue_depth * e->n_rings);
  out->in_flight = infl;
  out->deferred = def;
  out->fixed_bufs = fixed;
  out->pad = 0;
  out->pool_base = (uint64_t)(uintptr_t)e->pool;
}

int strom_open(strom_engine *e, const char *path, int flags) {
  int writable = flags & STROM_OPEN_WRITABLE;
  int oflags = writable ? (O_RDWR | O_CREAT) : O_RDONLY;
  int fdb = open(path, oflags, 0644);
  if (fdb < 0) return -errno;
  int fdd = (flags & STROM_OPEN_NO_DIRECT)
                ? -1
                : open(path, oflags | O_DIRECT, 0644);
  /* fdd == -1 is fine: tmpfs/overlayfs — all I/O takes the fallback path. */
  struct stat st;
  if (fstat(fdb, &st) != 0) {
    int err = -errno;
    close(fdb);
    if (fdd >= 0) close(fdd);
    return err;
  }
  int fh;
  int slot_b = -1, slot_d = -1;
  {
    std::lock_guard<std::mutex> g(e->files_mu);
    fh = e->next_fh++;
    FileEnt fe;
    fe.fd_direct = fdd;
    fe.fd_buffered = fdb;
    fe.size = (int64_t)st.st_size;
    fe.writable = writable != 0;
    if (e->reg_files_enabled) {
      /* Dynamic slot table: point registered slots at the new fds so
       * hot submissions ride IOSQE_FIXED_FILE.  Table full / kernel
       * refusal leaves the slots -1 — raw-fd submission, never an
       * error.  Slots are claimed (and reg_fds filled) HERE under
       * files_mu; the per-ring syscalls run below under restart_mu. */
      fe.slot_buffered = slot_b = e->reg_alloc_slot(fdb);
      fe.slot_direct = slot_d = e->reg_alloc_slot(fdd);
    }
    e->files[fh] = fe;
  }
  if (slot_b >= 0 || slot_d >= 0) {
    /* restart_mu excludes hot restarts (the only writer of a ring's
     * uring fd), so the FILES_UPDATE syscalls can never race a
     * teardown/rebuild onto a recycled descriptor.  Either ordering
     * with a restart is consistent: reg_fds already carries the new
     * fds, so a racing rebuild re-registers the complete table. */
    std::lock_guard<std::mutex> rg(e->restart_mu);
    if (slot_b >= 0) e->reg_update_all((uint32_t)slot_b, fdb);
    if (slot_d >= 0) e->reg_update_all((uint32_t)slot_d, fdd);
  }
  return fh;
}

int strom_close(strom_engine *e, int fh) {
  int slot_b, slot_d, fdd, fdb;
  {
    std::lock_guard<std::mutex> g(e->files_mu);
    auto it = e->files.find(fh);
    if (it == e->files.end()) return -EBADF;
    slot_b = it->second.slot_buffered;
    slot_d = it->second.slot_direct;
    fdd = it->second.fd_direct;
    fdb = it->second.fd_buffered;
    /* Table entries go -1 FIRST (a restart's re-register must not
     * resurrect slots for fds about to close); the slots become
     * re-allocatable only after the rings were updated below. */
    e->reg_clear_slot(slot_b);
    e->reg_clear_slot(slot_d);
    e->files.erase(it);
  }
  if (slot_b >= 0 || slot_d >= 0) {
    bool drained = true;
    {
      std::lock_guard<std::mutex> rg(e->restart_mu);
      /* A published-but-unconsumed SQE resolves IOSQE_FIXED_FILE slots
       * at CONSUMPTION time (SQPOLL thread / later flush): drain every
       * ring's SQ first, so any straggler referencing these slots
       * still resolves to OUR fds (held open until below).  Only then
       * may the slots point elsewhere. */
      for (auto &rcp : e->rings) {
        RingCtx *rc = rcp.get();
        if (rc->use_uring && rc->ring.reg_files)
          drained = rc->ring.drain_sq() && drained;
      }
      if (slot_b >= 0) e->reg_update_all((uint32_t)slot_b, -1);
      if (slot_d >= 0) e->reg_update_all((uint32_t)slot_d, -1);
    }
    std::lock_guard<std::mutex> g(e->files_mu);
    if (drained) {
      e->reg_recycle_slot(slot_b);
      e->reg_recycle_slot(slot_d);
    }
    /* !drained: LEAK the slot ids — a slot that might still be named
     * by an un-consumed SQE must never be recycled to another file
     * (the table entry is already -1, so nothing NEW can use it; the
     * 128-slot budget degrades to raw-fd submission long before this
     * matters). */
  }
  /* fds close LAST: every registered slot that pointed at them is
   * cleared, so no straggler submission can land in a recycled
   * descriptor. */
  if (fdd >= 0) close(fdd);
  close(fdb);
  return 0;
}

int64_t strom_file_size(strom_engine *e, int fh) {
  std::lock_guard<std::mutex> g(e->files_mu);
  auto it = e->files.find(fh);
  return it == e->files.end() ? -EBADF : it->second.size;
}

int strom_file_is_direct(strom_engine *e, int fh) {
  std::lock_guard<std::mutex> g(e->files_mu);
  auto it = e->files.find(fh);
  return it == e->files.end() ? -EBADF : (it->second.fd_direct >= 0 ? 1 : 0);
}

int strom_file_ident(strom_engine *e, int fh, uint64_t out[4]) {
  int fd;
  {
    std::lock_guard<std::mutex> g(e->files_mu);
    auto it = e->files.find(fh);
    if (it == e->files.end()) return -EBADF;
    fd = it->second.fd_buffered;
  }
  struct stat st;
  if (fstat(fd, &st) != 0) return -errno;
  out[0] = (uint64_t)st.st_dev;
  out[1] = (uint64_t)st.st_ino;
  out[2] = (uint64_t)st.st_mtim.tv_sec * 1000000000ull +
           (uint64_t)st.st_mtim.tv_nsec;
  out[3] = (uint64_t)st.st_size;
  return 0;
}

/* Shared submit body: validate + size-refresh under files_mu (leaf
 * lock), residency-probe with NO lock held, then stage on the chosen
 * ring under that ring's mutex only. */
static int64_t submit_read_on(strom_engine *e, RingCtx *rcx, int fh,
                              uint64_t offset, uint64_t len) {
  if (len > e->buf_bytes) return -EINVAL;
  if (e->stopping.load(std::memory_order_acquire)) return -ECANCELED;
  bool direct = false;
  int pfd = -1;
  int64_t fsize = 0;
  {
    std::lock_guard<std::mutex> g(e->files_mu);
    auto it = e->files.find(fh);
    if (it == e->files.end()) return -EBADF;
    /* Refresh size: the file may have grown since open. */
    struct stat st;
    if (fstat(it->second.fd_buffered, &st) == 0)
      it->second.size = (int64_t)st.st_size;
    fsize = it->second.size;
    direct = it->second.fd_direct >= 0;
    /* Residency-aware planning: if every page of the span is already in
     * the page cache, a buffered read is a memcpy and the NVMe
     * round-trip pure waste — CHOOSE the cache deliberately.  Counted
     * as bytes_resident (+fallback+bounce: the host copy is real),
     * never as a retry/rescue.  The probe's mmap/mincore syscalls run
     * OUTSIDE any lock (on a dup so a concurrent close cannot retarget
     * the fd) — a cold streaming submitter must not serialize behind
     * them. */
    if (direct && e->probe_residency && offset < (uint64_t)fsize)
      pfd = dup(it->second.fd_buffered);
  }
  bool resident = false;
  if (pfd >= 0) {
    uint64_t avail = std::min<uint64_t>(len, (uint64_t)fsize - offset);
    resident = span_resident(pfd, offset, avail);
    close(pfd);
  }
  Req *r = new Req();
  r->offset = offset;
  r->len = len;
  r->a_off = align_down(offset, e->alignment);
  r->a_len = align_up(offset + len, e->alignment) - r->a_off;
  r->direct = direct && !resident;
  r->planned_resident = direct && resident;
  r->fh = fh;
  r->rc = rcx;
  std::lock_guard<std::mutex> g(rcx->mu);
  if (e->stopping.load(std::memory_order_acquire)) {
    delete r;
    return -ECANCELED;
  }
  r->id = e->alloc_id(rcx);
  r->t_submit = now_ns();
  rcx->reqs[r->id] = r;
  e->st_sub.fetch_add(1, std::memory_order_relaxed);
  rcx->rg_sub.fetch_add(1, std::memory_order_relaxed);
  int got = e->acquire_or_defer(r);  /* never blocks the submitter */
  if (got > 0) {
    rcx->dispatch_locked(r);
  } else if (got < 0) {
    r->status = -ECANCELED;          /* raced engine destroy */
    rcx->complete_locked(r);
  }
  return r->id;
}

int64_t strom_submit_read(strom_engine *e, int fh, uint64_t offset,
                          uint64_t len) {
  return submit_read_on(e, e->pick_ring(), fh, offset, len);
}

int64_t strom_submit_read_ring(strom_engine *e, uint32_t ring, int fh,
                               uint64_t offset, uint64_t len) {
  if (ring >= e->n_rings) return -EINVAL;
  return submit_read_on(e, e->rings[ring].get(), fh, offset, len);
}

/* Shared vectored-submit body: the whole batch stages on ONE ring. */
static int submit_readv_on(strom_engine *e, RingCtx *rcx,
                           const strom_rd_ext *exts, uint32_t n,
                           int64_t *out_ids) {
  if (n == 0) return 0;
  for (uint32_t i = 0; i < n; i++)
    if (exts[i].length > e->buf_bytes) return -EINVAL;
  if (e->stopping.load(std::memory_order_acquire)) return -ECANCELED;
  /* Residency probes run with NO lock held (same discipline as
   * submit_read_on: mmap/mincore must not serialize other submitters;
   * dup so a concurrent close cannot retarget the fd). */
  struct Probe { uint32_t i; int pfd; uint64_t off, avail; };
  std::vector<Probe> probes;
  std::vector<char> resident(n, 0);
  std::vector<char> direct(n, 0);
  {
    /* Atomic validation + one size refresh per distinct fh under
     * files_mu: on any bad extent NOTHING has been submitted. */
    std::lock_guard<std::mutex> g(e->files_mu);
    std::unordered_map<int, int64_t> sized;
    for (uint32_t i = 0; i < n; i++) {
      auto it = e->files.find(exts[i].fh);
      if (it == e->files.end()) {
        for (auto &p : probes) close(p.pfd);
        return -EBADF;
      }
      if (sized.find(exts[i].fh) == sized.end()) {
        struct stat st;
        if (fstat(it->second.fd_buffered, &st) == 0)
          it->second.size = (int64_t)st.st_size;
        sized.emplace(exts[i].fh, it->second.size);
      }
      direct[i] = it->second.fd_direct >= 0 ? 1 : 0;
      if (direct[i] && e->probe_residency &&
          exts[i].offset < (uint64_t)it->second.size) {
        uint64_t avail = std::min<uint64_t>(
            exts[i].length, (uint64_t)it->second.size - exts[i].offset);
        int pfd = dup(it->second.fd_buffered);
        if (pfd >= 0)
          probes.push_back(Probe{i, pfd, exts[i].offset, avail});
      }
    }
  }
  for (auto &p : probes) {
    resident[p.i] = span_resident(p.pfd, p.off, p.avail) ? 1 : 0;
    close(p.pfd);
  }
  /* Stage every extent — uring SQEs publish WITHOUT ringing the
   * doorbell — then pay one io_uring_enter for the whole batch.
   * Only extents dispatched inline share that doorbell; extents that
   * defer on pool pressure ring their own when a buffer frees, so
   * they must not be credited as saved syscalls. */
  uint32_t inline_n = 0;
  std::lock_guard<std::mutex> g(rcx->mu);
  if (e->stopping.load(std::memory_order_acquire)) return -ECANCELED;
  for (uint32_t i = 0; i < n; i++) {
    const strom_rd_ext &x = exts[i];
    Req *r = new Req();
    r->offset = x.offset;
    r->len = x.length;
    r->a_off = align_down(x.offset, e->alignment);
    r->a_len = align_up(x.offset + x.length, e->alignment) - r->a_off;
    r->direct = direct[i] && !resident[i];
    r->planned_resident = direct[i] != 0 && resident[i] != 0;
    r->id = e->alloc_id(rcx);
    r->fh = x.fh;
    r->rc = rcx;
    r->t_submit = now_ns();
    rcx->reqs[r->id] = r;
    e->st_sub.fetch_add(1, std::memory_order_relaxed);
    rcx->rg_sub.fetch_add(1, std::memory_order_relaxed);
    out_ids[i] = r->id;
    int got = e->acquire_or_defer(r);  /* never blocks: deferred
                                          requests dispatch on the next
                                          buffer free */
    if (got > 0) {
      rcx->dispatch_locked(r, /*flush_now=*/false);
      inline_n++;
    } else if (got < 0) {
      r->status = -ECANCELED;          /* raced engine destroy */
      rcx->complete_locked(r);
    }
  }
  e->st_batches.fetch_add(1, std::memory_order_relaxed);
  if (inline_n > 1)
    e->st_sysc_saved.fetch_add(inline_n - 1, std::memory_order_relaxed);
  if (rcx->use_uring) rcx->ring.flush();
  return 0;
}

int strom_submit_readv(strom_engine *e, const strom_rd_ext *exts,
                       uint32_t n, int64_t *out_ids) {
  return submit_readv_on(e, e->pick_ring(), exts, n, out_ids);
}

int strom_submit_readv_ring(strom_engine *e, uint32_t ring,
                            const strom_rd_ext *exts, uint32_t n,
                            int64_t *out_ids) {
  if (ring >= e->n_rings) return -EINVAL;
  return submit_readv_on(e, e->rings[ring].get(), exts, n, out_ids);
}

static int fill_completion(Req *r, strom_completion *out) {
  if (out) {
    out->data = r->is_write ? nullptr
                            : r->buf + (r->offset - r->a_off);
    out->len = r->done_len;
    out->status = r->status;
    out->was_fallback = r->was_fallback ? 1 : 0;
    out->submit_ns = r->t_submit;
    out->complete_ns = r->t_complete;
  }
  return r->status;
}

int strom_wait(strom_engine *e, int64_t req_id, strom_completion *out) {
  RingCtx *rc = e->ring_of_id(req_id);
  if (!rc) return -ENOENT;
  std::unique_lock<std::mutex> lk(rc->mu);
  auto it = rc->reqs.find(req_id);
  if (it == rc->reqs.end()) return -ENOENT;
  Req *r = it->second;
  rc->cv_done.wait(lk, [&] { return r->state == ReqState::kDone; });
  return fill_completion(r, out);
}

int strom_wait_timeout(strom_engine *e, int64_t req_id,
                       strom_completion *out, uint64_t timeout_ns) {
  /* Hang DETECTION (SURVEY.md §5 failure detection): a stalled device
   * or wedged backend turns into -ETIMEDOUT the caller can act on
   * (diagnose, rescue, abort) instead of blocking forever.  The
   * request stays live — a timed-out wait may be retried. */
  RingCtx *rc = e->ring_of_id(req_id);
  if (!rc) return -ENOENT;
  std::unique_lock<std::mutex> lk(rc->mu);
  auto it = rc->reqs.find(req_id);
  if (it == rc->reqs.end()) return -ENOENT;
  Req *r = it->second;
  bool done = rc->cv_done.wait_for(
      lk, std::chrono::nanoseconds(timeout_ns),
      [&] { return r->state == ReqState::kDone; });
  if (!done) return -ETIMEDOUT;
  return fill_completion(r, out);
}

int strom_release(strom_engine *e, int64_t req_id) {
  RingCtx *rc = e->ring_of_id(req_id);
  if (!rc) return -ENOENT;
  int buf_idx = -1;
  {
    std::lock_guard<std::mutex> g(rc->mu);
    auto it = rc->reqs.find(req_id);
    if (it == rc->reqs.end()) return -ENOENT;
    Req *r = it->second;
    if (r->state != ReqState::kDone) return -EBUSY;
    buf_idx = r->buf_idx;
    rc->reqs.erase(it);
    delete r;
  }
  /* Buffer handoff runs with no ring lock held: the recipient may live
   * on a DIFFERENT ring (global deferral FIFO), and two ring mutexes
   * must never nest. */
  if (buf_idx >= 0) e->recycle_buffer(buf_idx);
  return 0;
}

static int64_t submit_write_on(strom_engine *e, RingCtx *rcx, int fh,
                               uint64_t offset, const void *src,
                               uint64_t len) {
  if (e->stopping.load(std::memory_order_acquire)) return -ECANCELED;
  bool conformant;
  {
    std::lock_guard<std::mutex> g(e->files_mu);
    auto it = e->files.find(fh);
    if (it == e->files.end()) return -EBADF;
    if (!it->second.writable) return -EACCES;
    conformant = ((uint64_t)src % e->alignment == 0) &&
                 (offset % e->alignment == 0) &&
                 (len % e->alignment == 0) && it->second.fd_direct >= 0;
  }
  if (!conformant && len > e->buf_bytes) return -EINVAL;
  Req *r = new Req();
  r->is_write = true;
  r->fh = fh;
  r->rc = rcx;
  r->offset = offset;
  r->len = len;
  r->direct = conformant;
  r->wsrc = src; /* wrapper keeps src alive until wait() */
  std::lock_guard<std::mutex> g(rcx->mu);
  if (e->stopping.load(std::memory_order_acquire)) {
    delete r;
    return -ECANCELED;
  }
  r->id = e->alloc_id(rcx);
  r->t_submit = now_ns();
  rcx->reqs[r->id] = r;
  e->st_sub.fetch_add(1, std::memory_order_relaxed);
  rcx->rg_sub.fetch_add(1, std::memory_order_relaxed);
  if (conformant) {
    /* zero-copy: O_DIRECT DMA straight from caller memory, no buffer */
    r->buf_idx = -1;
    rcx->dispatch_locked(r);
    return r->id;
  }
  int got = e->acquire_or_defer(r);  /* else staged when a buffer frees */
  if (got > 0) {
    memcpy(r->buf, src, len); /* the one counted bounce */
    e->st_bounce.fetch_add(len, std::memory_order_relaxed);
    rcx->dispatch_locked(r);
  } else if (got < 0) {
    r->status = -ECANCELED;          /* raced engine destroy */
    rcx->complete_locked(r);
  }
  return r->id;
}

int64_t strom_submit_write(strom_engine *e, int fh, uint64_t offset,
                           const void *src, uint64_t len) {
  return submit_write_on(e, e->pick_ring(), fh, offset, src, len);
}

int64_t strom_submit_write_ring(strom_engine *e, uint32_t ring, int fh,
                                uint64_t offset, const void *src,
                                uint64_t len) {
  if (ring >= e->n_rings) return -EINVAL;
  return submit_write_on(e, e->rings[ring].get(), fh, offset, src, len);
}

void strom_get_stats(strom_engine *e, strom_stats_blk *out) {
  out->bytes_direct = e->st_direct.load(std::memory_order_relaxed);
  out->bytes_fallback = e->st_fallback.load(std::memory_order_relaxed);
  out->bounce_bytes = e->st_bounce.load(std::memory_order_relaxed);
  out->bytes_written_direct = e->st_written.load(std::memory_order_relaxed);
  /* completed is read BEFORE submitted, acquire paired with the release
   * increment in complete_locked: any completion the observer sees
   * implies visibility of its submission, so completed <= submitted
   * always holds in the snapshot. */
  out->requests_completed = e->st_comp.load(std::memory_order_acquire);
  out->requests_submitted = e->st_sub.load(std::memory_order_relaxed);
  out->requests_failed = e->st_fail.load(std::memory_order_relaxed);
  out->retries = e->st_retry.load(std::memory_order_relaxed);
  out->bytes_resident = e->st_resident.load(std::memory_order_relaxed);
  out->submit_batches = e->st_batches.load(std::memory_order_relaxed);
  out->submit_syscalls_saved =
      e->st_sysc_saved.load(std::memory_order_relaxed);
  out->submit_enters = e->st_enters.load(std::memory_order_relaxed);
}

void strom_drain_stats(strom_engine *e, strom_stats_blk *out) {
  out->bytes_direct = e->st_direct.exchange(0, std::memory_order_acq_rel);
  out->bytes_fallback = e->st_fallback.exchange(0, std::memory_order_acq_rel);
  out->bounce_bytes = e->st_bounce.exchange(0, std::memory_order_acq_rel);
  out->bytes_written_direct =
      e->st_written.exchange(0, std::memory_order_acq_rel);
  out->requests_submitted = e->st_sub.exchange(0, std::memory_order_acq_rel);
  out->requests_completed = e->st_comp.exchange(0, std::memory_order_acq_rel);
  out->requests_failed = e->st_fail.exchange(0, std::memory_order_acq_rel);
  out->retries = e->st_retry.exchange(0, std::memory_order_acq_rel);
  out->bytes_resident = e->st_resident.exchange(0, std::memory_order_acq_rel);
  out->submit_batches = e->st_batches.exchange(0, std::memory_order_acq_rel);
  out->submit_syscalls_saved =
      e->st_sysc_saved.exchange(0, std::memory_order_acq_rel);
  out->submit_enters = e->st_enters.exchange(0, std::memory_order_acq_rel);
}

void strom_reset_stats(strom_engine *e) {
  e->st_direct = 0; e->st_fallback = 0; e->st_bounce = 0; e->st_written = 0;
  e->st_sub = 0; e->st_comp = 0; e->st_fail = 0; e->st_retry = 0;
  e->st_resident = 0; e->st_batches = 0; e->st_sysc_saved = 0;
  e->st_enters = 0;
  for (int i = 0; i < STROM_LAT_BUCKETS; i++) {
    e->lat_read[i].store(0, std::memory_order_relaxed);
    e->lat_write[i].store(0, std::memory_order_relaxed);
  }
}

int strom_backend_is_uring(strom_engine *e) {
  return (!e->rings.empty() && e->rings[0]->use_uring) ? 1 : 0;
}

void strom_get_latency(strom_engine *e,
                       uint64_t out_read[STROM_LAT_BUCKETS],
                       uint64_t out_write[STROM_LAT_BUCKETS]) {
  for (int i = 0; i < STROM_LAT_BUCKETS; i++) {
    if (out_read)
      out_read[i] = e->lat_read[i].load(std::memory_order_relaxed);
    if (out_write)
      out_write[i] = e->lat_write[i].load(std::memory_order_relaxed);
  }
}

/* ---------------- crc32c (Castagnoli) ---------------- */

static uint32_t g_crc_tbl[8][256];
static bool g_crc_init = false;

static void crc_init_tables() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
    g_crc_tbl[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = g_crc_tbl[0][i];
    for (int t = 1; t < 8; t++) {
      c = g_crc_tbl[0][c & 0xFF] ^ (c >> 8);
      g_crc_tbl[t][i] = c;
    }
  }
  g_crc_init = true;
}

#if defined(__x86_64__)
#include <cpuid.h>
static bool has_sse42() {
  unsigned a, b, c, d;
  if (!__get_cpuid(1, &a, &b, &c, &d)) return false;
  return (c & (1u << 20)) != 0;
}
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(const uint8_t *p, uint64_t n, uint32_t c) {
  while (n && ((uintptr_t)p & 7)) { c = __builtin_ia32_crc32qi(c, *p++); n--; }
  uint64_t c64 = c;
  while (n >= 8) {
    c64 = __builtin_ia32_crc32di(c64, *(const uint64_t *)p);
    p += 8;
    n -= 8;
  }
  c = (uint32_t)c64;
  while (n--) c = __builtin_ia32_crc32qi(c, *p++);
  return c;
}
#endif

uint32_t strom_crc32c(const void *data, uint64_t len, uint32_t crc) {
  if (!g_crc_init) crc_init_tables();
  const uint8_t *p = (const uint8_t *)data;
  uint32_t c = ~crc;
#if defined(__x86_64__)
  static int hw = -1;
  if (hw < 0) hw = has_sse42() ? 1 : 0;
  if (hw) return ~crc32c_hw(p, len, c);
#endif
  while (len && ((uintptr_t)p & 7)) {
    c = g_crc_tbl[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
    len--;
  }
  while (len >= 8) {
    uint64_t w;
    memcpy(&w, p, 8);
    w ^= c;
    c = g_crc_tbl[7][w & 0xFF] ^ g_crc_tbl[6][(w >> 8) & 0xFF] ^
        g_crc_tbl[5][(w >> 16) & 0xFF] ^ g_crc_tbl[4][(w >> 24) & 0xFF] ^
        g_crc_tbl[3][(w >> 32) & 0xFF] ^ g_crc_tbl[2][(w >> 40) & 0xFF] ^
        g_crc_tbl[1][(w >> 48) & 0xFF] ^ g_crc_tbl[0][(w >> 56) & 0xFF];
    p += 8;
    len -= 8;
  }
  while (len--) c = g_crc_tbl[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
  return ~c;
}

/* ------------- pinned host-DRAM cache arena (io/hostcache.py) ------------- */

void *strom_hostcache_arena_create(uint64_t bytes, int lock_pages,
                                   int32_t *locked_out) {
  if (locked_out) *locked_out = 0;
  if (bytes == 0) {
    errno = EINVAL;
    return NULL;
  }
  void *base = mmap(NULL, bytes, PROT_READ | PROT_WRITE,
                    MAP_PRIVATE | MAP_ANONYMOUS | MAP_POPULATE, -1, 0);
  if (base == MAP_FAILED) {
    /* MAP_POPULATE can fail on exotic kernels; the arena is still
     * usable unfaulted — retry plain before giving up. */
    base = mmap(NULL, bytes, PROT_READ | PROT_WRITE,
                MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (base == MAP_FAILED) return NULL;
  }
  if (lock_pages && mlock(base, bytes) == 0 && locked_out)
    *locked_out = 1; /* best-effort: RLIMIT_MEMLOCK refusal is not fatal */
  return base;
}

void strom_hostcache_arena_destroy(void *base, uint64_t bytes) {
  if (base && bytes) munmap(base, bytes); /* munlock implied */
}

void strom_hostcache_copy(void *dst, const void *src, uint64_t bytes) {
  if (dst && src && bytes) memcpy(dst, src, bytes);
}

}  /* extern "C" */

/* ------------------------- tar shard indexer ------------------------- */

/* Octal field (NUL/space padded), with GNU base-256 (first byte 0x80)
 * for sizes beyond 8 GiB.  Returns -1 on garbage. */
static int64_t tar_num(const uint8_t *f, size_t n) {
  if (f[0] & 0x80) {               /* base-256 */
    uint64_t v = f[0] & 0x7F;
    for (size_t i = 1; i < n; i++) v = (v << 8) | f[i];
    return (int64_t)v;
  }
  int64_t v = 0;
  size_t i = 0;
  while (i < n && (f[i] == ' ')) i++;
  for (; i < n && f[i] >= '0' && f[i] <= '7'; i++)
    v = v * 8 + (f[i] - '0');
  return v;
}

static int tar_checksum_ok(const uint8_t *h) {
  int64_t want = tar_num(h + 148, 8);
  if (want < 0) return 0;
  uint64_t sum = 0;
  for (int i = 0; i < 512; i++)
    sum += (i >= 148 && i < 156) ? ' ' : h[i];
  return (int64_t)sum == want;
}

namespace {
struct TarBuf {               /* growable packed result */
  uint8_t *p = nullptr;
  uint64_t len = 0, cap = 0;
  bool push(uint64_t off, uint64_t size, const char *name, uint32_t nl) {
    uint64_t need = len + 8 + 8 + 4 + nl;
    if (need > cap) {
      uint64_t ncap = cap ? cap * 2 : 4096;
      while (ncap < need) ncap *= 2;
      uint8_t *np = (uint8_t *)realloc(p, ncap);
      if (!np) return false;
      p = np; cap = ncap;
    }
    memcpy(p + len, &off, 8);
    memcpy(p + len + 8, &size, 8);
    memcpy(p + len + 16, &nl, 4);
    memcpy(p + len + 20, name, nl);
    len = need;
    return true;
  }
};

/* pax "len key=value\n" records: extract path= / size= overrides.
 * Returns 0; -1 on a malformed record (caller: -EBADMSG — never a
 * silent partial parse: kvlen underflow here was an OOB heap read
 * before 2026-07-31); -2 when a path exceeds path_cap — a VALID
 * archive this walker just doesn't support (caller: -ENOTSUP, so the
 * Python side can fall back to tarfile). */
static int pax_parse(const uint8_t *data, size_t n, char *path_out,
                     size_t path_cap, int *have_path,
                     int64_t *size_out, int *have_size) {
  size_t i = 0;
  while (i < n) {
    size_t reclen = 0, j = i;
    while (j < n && data[j] >= '0' && data[j] <= '9') {
      reclen = reclen * 10 + (data[j++] - '0');
      if (reclen > n) return -1;       /* bounds the accumulation too */
    }
    if (j >= n || data[j] != ' ' || reclen == 0 || i + reclen > n)
      return -1;
    size_t hdr = (j + 1) - i;          /* digits + space */
    if (reclen < hdr + 1 || data[i + reclen - 1] != '\n') return -1;
    const uint8_t *kv = data + j + 1;
    size_t kvlen = reclen - hdr - 1;   /* minus trailing \n */
    if (kvlen > 5 && memcmp(kv, "path=", 5) == 0) {
      size_t pl = kvlen - 5;
      if (pl >= path_cap) return -2;   /* valid archive, name beyond our
                                        * cap: unsupported, not corrupt */
      memcpy(path_out, kv + 5, pl);
      path_out[pl] = 0;
      *have_path = 1;
    } else if (kvlen > 5 && memcmp(kv, "size=", 5) == 0) {
      int64_t v = 0;
      for (size_t k = 5; k < kvlen; k++)
        if (kv[k] >= '0' && kv[k] <= '9') v = v * 10 + (kv[k] - '0');
      *size_out = v;
      *have_size = 1;
    }
    i += reclen;
  }
  return 0;
}
}  /* namespace */

extern "C" int64_t strom_tar_index(const char *path, uint8_t **out,
                                   uint64_t *out_bytes) {
  *out = nullptr;
  *out_bytes = 0;
  /* O_DIRECT first: the header walk faults its windows through the
   * page cache otherwise, and a resident member span makes the
   * engine's submit-time mincore planner deliberately choose the
   * buffered path for every member read that follows — one index pass
   * silently demoting the O_DIRECT pipeline to memcpy (a cold wds_raw
   * epoch measured 100% fallback+bounce from exactly this).  Direct
   * windows bypass the cache entirely — no pollution AND no eviction
   * of pages that were legitimately warm before the walk. */
  int direct = 1;
  int fd = open(path, O_RDONLY | O_CLOEXEC | O_DIRECT);
  if (fd < 0) { direct = 0; fd = open(path, O_RDONLY | O_CLOEXEC); }
  if (fd < 0) return -errno;
  struct stat st;
  if (fstat(fd, &st) != 0) { int e = errno; close(fd); return -e; }
  TarBuf buf;
  /* name overrides pending for the NEXT header (GNU 'L' / pax 'x') */
  char longname[4097];
  int have_long = 0;
  int64_t pax_size = -1;
  int have_pax_size = 0;
  int64_t count = 0;
  uint64_t off = 0;
  uint8_t h[512];
  int zeros = 0;
  /* windowed header reads: one 4 MiB pread serves ~1k headers of a
   * small-member shard instead of one syscall each (the syscall loop
   * measured 4.5x tarfile; the window ~3x further).  Large members
   * simply land the next header outside the window and trigger a
   * refill at the new offset — a seek, not a full-file read. */
  enum { WIN = 4 << 20 };
  uint8_t *win = nullptr;
  if (posix_memalign((void **)&win, 4096, WIN) != 0 || !win) {
    close(fd); return -ENOMEM;
  }
  uint64_t win_off = 0, win_len = 0;
  /* Every archive byte the walk touches — headers AND 'L'/'x'/'g'
   * payloads — goes through this one window fill, so the direct-mode
   * alignment rules hold everywhere (a stray unaligned pread on the
   * O_DIRECT fd EINVALs on ext4, which would silently demote every
   * pax-format archive to the polluting Python fallback). */
  int ferr = 0;
  auto fill = [&](uint64_t o, uint64_t need) -> uint8_t * {
    if (need == 0) return win;
    if (need > (uint64_t)WIN) { ferr = -ENOTSUP; return nullptr; }
    if (o < win_off || o + need > win_off + win_len) {
      uint64_t roff = direct ? (o & ~(uint64_t)4095) : o;
      ssize_t got = pread(fd, win, WIN, (off_t)roff);
      if (got < 0 && direct) {
        /* fs accepted O_DIRECT open but refuses the read: reopen
         * buffered once and continue the walk.  Keep the ORIGINAL
         * read errno if the reopen fails — a media error must not
         * masquerade as an fd-limit problem. */
        int rerr = errno;
        int bfd = open(path, O_RDONLY | O_CLOEXEC);
        if (bfd >= 0) {
          close(fd); fd = bfd; direct = 0; roff = o;
          got = pread(fd, win, WIN, (off_t)roff);
        } else {
          errno = rerr;
        }
      }
      if (got < 0) { ferr = -errno; return nullptr; }
      if ((uint64_t)got < (o - roff) + need) {
        ferr = -EBADMSG;              /* genuinely short: truncated */
        return nullptr;
      }
      win_off = roff;
      win_len = (uint64_t)got;
    }
    return win + (o - win_off);
  };
  while ((int64_t)(off + 512) <= st.st_size) {
    uint8_t *hp = fill(off, 512);
    if (!hp) { close(fd); free(win); free(buf.p); return ferr; }
    memcpy(h, hp, 512);
    int allz = 1;
    for (int i = 0; i < 512 && allz; i++) allz = (h[i] == 0);
    if (allz) {
      if (++zeros == 2) break;       /* end-of-archive marker */
      off += 512;
      continue;
    }
    zeros = 0;
    if (!tar_checksum_ok(h)) { close(fd); free(win); free(buf.p);
                           return -EBADMSG; }
    int64_t size = tar_num(h + 124, 12);
    if (size < 0) { close(fd); free(win); free(buf.p);
                return -EBADMSG; }
    uint8_t type = h[156];
    uint64_t data = off + 512;
    uint64_t adv = 512 + (((uint64_t)size + 511) & ~511ULL);
    if (type == 'L' || type == 'x' || type == 'g') {
      /* 'L'/'x' override the NEXT real header; 'g' sets GLOBAL pax
       * defaults.  Error split (advisor round-3): -EBADMSG only for
       * genuine corruption; a VALID archive using a feature this
       * walker doesn't implement returns -ENOTSUP so the caller can
       * fall back to tarfile instead of failing where it used to
       * succeed. */
      size_t n = (size_t)size;
      if (n > sizeof(longname) * 4) { close(fd); free(win);
                                free(buf.p); return -ENOTSUP; }
      uint8_t *tmp = (uint8_t *)malloc(n + 1);
      if (!tmp) { close(fd); free(win); free(buf.p); return -ENOMEM; }
      uint8_t *pp = fill(data, n);
      if (!pp) {
        free(tmp); close(fd); free(win); free(buf.p);
        return ferr;
      }
      memcpy(tmp, pp, n);
      tmp[n] = 0;
      int bad = 0;                   /* -EBADMSG: corrupt */
      int unsup = 0;                 /* -ENOTSUP: valid, unimplemented */
      if (type == 'L') {
        size_t nl = strnlen((char *)tmp, n);
        if (nl >= sizeof(longname)) unsup = 1;  /* loud, never a silent
                                                   truncated member key */
        else {
          memcpy(longname, tmp, nl);
          longname[nl] = 0;
          have_long = 1;
        }
      } else if (type == 'g') {
        /* Parse the global payload into throwaway slots purely to
         * CLASSIFY it: global path=/size= overrides would change every
         * later member's identity — indexing with raw header fields
         * would be silently wrong, so that's unsupported; globals that
         * carry neither (comment=, mtime=, ...) are safely ignored. */
        char gpath[4097];
        int g_have_path = 0, g_have_size = 0;
        int64_t g_size = -1;
        int rc = pax_parse(tmp, n, gpath, sizeof(gpath),
                           &g_have_path, &g_size, &g_have_size);
        if (rc == -2) unsup = 1;
        else if (rc != 0) bad = 1;
        else if (g_have_path || g_have_size) unsup = 1;
      } else {
        int rc = pax_parse(tmp, n, longname, sizeof(longname),
                           &have_long, &pax_size, &have_pax_size);
        if (rc == -2) unsup = 1;
        else if (rc != 0) bad = 1;
      }
      free(tmp);
      if (bad || unsup) { close(fd); free(win); free(buf.p);
                          return bad ? -EBADMSG : -ENOTSUP; }
      off += adv;
      continue;
    }
    if (have_pax_size) {            /* pax size overrides the header's */
      size = pax_size;
      adv = 512 + (((uint64_t)size + 511) & ~511ULL);
      have_pax_size = 0;
      pax_size = -1;
    }
    if (type == '0' || type == 0) {  /* regular file */
      /* the member's data must actually exist — a truncated archive
       * yields a loud error, never a partial index */
      if ((int64_t)(data + (uint64_t)size) > st.st_size) {
        close(fd); free(win); free(buf.p); return -EBADMSG;
      }
      char name[4097];
      if (have_long) {
        size_t nl = strnlen(longname, sizeof(longname) - 1);
        memcpy(name, longname, nl);
        name[nl] = 0;
      } else {
        /* ustar: prefix (155) "/" name (100) */
        char nm[101], pf[156];
        memcpy(nm, h, 100); nm[100] = 0;
        memcpy(pf, h + 345, 155); pf[155] = 0;
        int has_ustar = (memcmp(h + 257, "ustar", 5) == 0);
        if (has_ustar && pf[0]) snprintf(name, sizeof(name),
                                         "%s/%s", pf, nm);
        else snprintf(name, sizeof(name), "%s", nm);
      }
      uint32_t nl = (uint32_t)strnlen(name, sizeof(name) - 1);
      if (!buf.push(data, (uint64_t)size, name, nl)) {
        close(fd); free(win); free(buf.p); return -ENOMEM;
      }
      count++;
    }
    have_long = 0;
    off += adv;
  }
  close(fd);
  free(win);
  *out = buf.p;
  *out_bytes = buf.len;
  return count;
}

extern "C" void strom_tar_index_free(uint8_t *buf) { free(buf); }
