/* stress_test — concurrency stress harness for the strom-io engine.
 *
 * SURVEY.md §5 "Race detection": the reference has nothing beyond kernel
 * lockdep; the promised TPU-build upgrade is TSAN + stress tests for the
 * C++ engine.  This binary hammers one engine from many threads at once:
 *
 *   - reader threads: random-offset reads, each verified against the
 *     deterministic content pattern (catches buffer-recycling races);
 *   - a writer thread appending to a scratch file;
 *   - burst-writer threads keeping several submit_writes in flight at
 *     once (the checkpoint/offload pipelined-write pattern), each with
 *     length verification of the completion;
 *   - a mixed thread alternating submit_write with submit_readv batches
 *     on the same ring (write path racing the vectored read path);
 *   - an observer thread polling stats/pool-info/latency (lock-free
 *     counter reads racing the hot path);
 *   - an open/close churn thread (file-table mutation under I/O).
 *
 * Build plain (`make stress`) for the functional stress run, or with
 * ThreadSanitizer (`make tsan`) to turn every data race into a
 * report.  Exit code 0 = no mismatches, no request failures; TSAN adds
 * its own non-zero exit on findings.
 *
 * Usage: stress_test [iters-per-thread] [n-readers] [tmpdir]
 */

#include "strom_io.h"

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

namespace {

constexpr uint64_t kFileBytes = 8ull << 20;
constexpr uint64_t kMaxRead = 256 * 1024;

/* Deterministic byte pattern: content is a pure function of offset, so a
 * read of any range verifies without a reference buffer. */
inline uint8_t pat(uint64_t off) {
  return (uint8_t)((off * 2654435761ull) >> 7);
}

std::atomic<uint64_t> g_errors{0};

void fail(const char *what) {
  fprintf(stderr, "stress: FAIL %s\n", what);
  g_errors.fetch_add(1);
}

/* xorshift — per-thread deterministic RNG, no libc rand() races. */
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed * 0x9E3779B97F4A7C15ull + 1) {}
  uint64_t next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
};

void reader_thread(strom_engine *eng, int fh, int iters, int seed) {
  Rng rng(seed);
  for (int i = 0; i < iters; i++) {
    uint64_t off = rng.next() % (kFileBytes - 1);
    uint64_t len = 1 + rng.next() % kMaxRead;
    if (off + len > kFileBytes) len = kFileBytes - off;
    int64_t id = strom_submit_read(eng, fh, off, len);
    if (id < 0) { fail("submit_read"); continue; }
    strom_completion c;
    if (strom_wait(eng, id, &c) != 0 || c.status != 0) {
      fail("read status");
      strom_release(eng, id);
      continue;
    }
    if (c.len != len) fail("short read");
    for (uint64_t k = 0; k < c.len; k += 997)  /* sparse verify: cheap */
      if (c.data[k] != pat(off + k)) { fail("payload mismatch"); break; }
    strom_release(eng, id);
  }
}

/* Vectored submitter: batches of random extents through
 * strom_submit_readv, racing the scalar readers for buffers and the
 * deferred-flush doorbell against concurrent dispatches. */
void readv_thread(strom_engine *eng, int fh, int iters, int seed) {
  Rng rng(seed * 7919 + 3);
  for (int i = 0; i < iters; i++) {
    const uint32_t n = 1 + (uint32_t)(rng.next() % 8);
    strom_rd_ext exts[8];
    for (uint32_t j = 0; j < n; j++) {
      uint64_t off = rng.next() % (kFileBytes - 1);
      uint64_t len = 1 + rng.next() % (kMaxRead / 4);
      if (off + len > kFileBytes) len = kFileBytes - off;
      exts[j] = strom_rd_ext{fh, 0, off, len};
    }
    int64_t ids[8];
    if (strom_submit_readv(eng, exts, n, ids) != 0) {
      fail("submit_readv");
      continue;
    }
    for (uint32_t j = 0; j < n; j++) {
      strom_completion c;
      if (strom_wait(eng, ids[j], &c) != 0 || c.status != 0) {
        fail("readv status");
        strom_release(eng, ids[j]);
        continue;
      }
      if (c.len != exts[j].length) fail("readv short");
      for (uint64_t k = 0; k < c.len; k += 997)
        if (c.data[k] != pat(exts[j].offset + k)) {
          fail("readv payload mismatch");
          break;
        }
      strom_release(eng, ids[j]);
    }
  }
}

/* Restart-tolerant vectored reader: the hot-restart phase's consumer.
 * A completion cancelled by a ring restart (-ECANCELED) is RESUBMITTED
 * round-robin (the Python supervision layer's requeue path, here in
 * miniature) and must then verify — any other error, short read, or
 * payload mismatch is a hard failure.  Counts requeues so the phase
 * can assert the restart actually cancelled something. */
void restart_reader_thread(strom_engine *eng, int fh, int iters, int seed,
                           std::atomic<uint64_t> *requeued) {
  Rng rng(seed * 104729 + 11);
  for (int i = 0; i < iters; i++) {
    const uint32_t n = 1 + (uint32_t)(rng.next() % 4);
    strom_rd_ext exts[4];
    for (uint32_t j = 0; j < n; j++) {
      uint64_t off = rng.next() % (kFileBytes - 1);
      uint64_t len = 1 + rng.next() % (kMaxRead / 8);
      if (off + len > kFileBytes) len = kFileBytes - off;
      exts[j] = strom_rd_ext{fh, 0, off, len};
    }
    int64_t ids[4];
    uint32_t ring = (uint32_t)(rng.next() % 2); /* rings 0-1; 1 restarts */
    if (strom_submit_readv_ring(eng, ring, exts, n, ids) != 0) {
      fail("restart submit_readv_ring");
      continue;
    }
    for (uint32_t j = 0; j < n; j++) {
      int64_t id = ids[j];
      for (int attempt = 0; attempt < 64; attempt++) {
        strom_completion c;
        int rc = strom_wait(eng, id, &c);
        if (rc == -ECANCELED) {
          /* requeue: release the cancelled request, resubmit the same
           * range (round-robin — lands on whichever ring is healthy) */
          strom_release(eng, id);
          requeued->fetch_add(1);
          id = strom_submit_read(eng, fh, exts[j].offset, exts[j].length);
          if (id < 0) { fail("requeue resubmit"); break; }
          continue;
        }
        if (rc != 0 || c.status != 0) {
          fail("restart-phase read status");
          strom_release(eng, id);
          break;
        }
        if (c.len != exts[j].length) fail("restart-phase short read");
        for (uint64_t k = 0; k < c.len; k += 997)
          if (c.data[k] != pat(exts[j].offset + k)) {
            fail("restart-phase payload mismatch");
            break;
          }
        strom_release(eng, id);
        break;
      }
    }
  }
}

void writer_thread(strom_engine *eng, const std::string &dir, int iters) {
  std::string path = dir + "/stress_w.bin";
  int fh = strom_open(eng, path.c_str(), STROM_OPEN_WRITABLE);
  if (fh < 0) { fail("open writable"); return; }
  std::vector<uint8_t> buf(64 * 1024);
  Rng rng(0xAB07);
  for (int i = 0; i < iters; i++) {
    uint64_t off = (rng.next() % 64) * buf.size();
    for (size_t k = 0; k < buf.size(); k++) buf[k] = pat(off + k);
    int64_t id = strom_submit_write(eng, fh, off, buf.data(), buf.size());
    if (id < 0) { fail("submit_write"); continue; }
    strom_completion c;
    if (strom_wait(eng, id, &c) != 0) fail("write wait");
    strom_release(eng, id);
  }
  strom_close(eng, fh);
}

/* Restart-tolerant writer: like writer_thread, but a -ECANCELED
 * completion (the request parked on a ring being hot-restarted) is the
 * REQUEUE contract, not damage — resubmit the same range, exactly as
 * ResilientWrite's retry does.  Used by phases that restart rings
 * under live write traffic. */
void restart_writer_thread(strom_engine *eng, const std::string &dir,
                           int iters, int seed) {
  std::string path = dir + "/stress_rw" + std::to_string(seed) + ".bin";
  int fh = strom_open(eng, path.c_str(), STROM_OPEN_WRITABLE);
  if (fh < 0) { fail("open restart writable"); return; }
  std::vector<uint8_t> buf(64 * 1024);
  Rng rng(seed * 6700417 + 3);
  for (int i = 0; i < iters; i++) {
    uint64_t off = (rng.next() % 64) * buf.size();
    for (size_t k = 0; k < buf.size(); k++) buf[k] = pat(off + k);
    int64_t id = strom_submit_write(eng, fh, off, buf.data(), buf.size());
    if (id < 0) { fail("restart submit_write"); continue; }
    for (int attempt = 0; attempt < 64; attempt++) {
      strom_completion c;
      int rc = strom_wait(eng, id, &c);
      int st = rc == 0 ? c.status : rc;
      strom_release(eng, id);
      if (st == -ECANCELED) {
        id = strom_submit_write(eng, fh, off, buf.data(), buf.size());
        if (id < 0) { fail("restart write resubmit"); break; }
        continue;
      }
      if (st != 0) fail("restart write status");
      break;
    }
  }
  strom_close(eng, fh);
  unlink(path.c_str());
}

/* Pipelined writer: keeps kBurst submit_writes in flight on one fh
 * (each source buffer owned until its wait returns), racing the readv
 * batches and scalar readers for ring slots and pool buffers — the
 * write half of the checkpoint/offload submit pattern, on ONE ring.
 * Each thread owns a disjoint file so content verification stays a
 * pure function of (seed, offset). */
void writer_burst_thread(strom_engine *eng, const std::string &dir,
                         int iters, int seed) {
  constexpr int kBurst = 6;
  std::string path = dir + "/stress_wb" + std::to_string(seed) + ".bin";
  int fh = strom_open(eng, path.c_str(), STROM_OPEN_WRITABLE);
  if (fh < 0) { fail("open burst writable"); return; }
  struct Slot { int64_t id; uint64_t len; std::vector<uint8_t> buf; };
  std::vector<Slot> inflight;
  Rng rng(seed * 131071 + 17);
  auto drain_one = [&]() {
    Slot s = std::move(inflight.front());
    inflight.erase(inflight.begin());
    strom_completion c;
    if (strom_wait(eng, s.id, &c) != 0 || c.status != 0)
      fail("burst write status");
    else if (c.len != s.len)
      fail("burst short write");
    strom_release(eng, s.id);
  };
  for (int i = 0; i < iters; i++) {
    uint64_t off = (rng.next() % 128) * 4096;
    uint64_t len = 1 + rng.next() % (64 * 1024);
    Slot s;
    s.len = len;
    s.buf.resize(len);
    for (uint64_t k = 0; k < len; k++) s.buf[k] = pat(off + k);
    s.id = strom_submit_write(eng, fh, off, s.buf.data(), len);
    if (s.id < 0) { fail("burst submit_write"); continue; }
    inflight.push_back(std::move(s));
    while ((int)inflight.size() >= kBurst) drain_one();
  }
  while (!inflight.empty()) drain_one();
  strom_close(eng, fh);
  unlink(path.c_str());
}

/* Mixed submitter: alternates a write and a readv batch on the SAME
 * ring iteration — the exact interleaving a checkpoint save overlapping
 * a loader epoch produces (submit_write and submit_readv racing for the
 * SQ and the deferred-dispatch queue). */
void mixed_rw_thread(strom_engine *eng, int read_fh, const std::string &dir,
                     int iters, int seed) {
  std::string path = dir + "/stress_mx" + std::to_string(seed) + ".bin";
  int wfh = strom_open(eng, path.c_str(), STROM_OPEN_WRITABLE);
  if (wfh < 0) { fail("open mixed writable"); return; }
  Rng rng(seed * 524287 + 29);
  std::vector<uint8_t> wbuf(16 * 1024);
  for (int i = 0; i < iters; i++) {
    uint64_t woff = (rng.next() % 32) * wbuf.size();
    for (size_t k = 0; k < wbuf.size(); k++) wbuf[k] = pat(woff + k);
    int64_t wid = strom_submit_write(eng, wfh, woff, wbuf.data(),
                                     wbuf.size());
    strom_rd_ext exts[4];
    const uint32_t n = 1 + (uint32_t)(rng.next() % 4);
    for (uint32_t j = 0; j < n; j++) {
      uint64_t off = rng.next() % (kFileBytes - 1);
      uint64_t len = 1 + rng.next() % (kMaxRead / 8);
      if (off + len > kFileBytes) len = kFileBytes - off;
      exts[j] = strom_rd_ext{read_fh, 0, off, len};
    }
    int64_t ids[4];
    if (strom_submit_readv(eng, exts, n, ids) != 0) {
      fail("mixed submit_readv");
    } else {
      for (uint32_t j = 0; j < n; j++) {
        strom_completion c;
        if (strom_wait(eng, ids[j], &c) != 0 || c.status != 0)
          fail("mixed readv status");
        else
          for (uint64_t k = 0; k < c.len; k += 997)
            if (c.data[k] != pat(exts[j].offset + k)) {
              fail("mixed readv payload");
              break;
            }
        strom_release(eng, ids[j]);
      }
    }
    if (wid < 0) {
      fail("mixed submit_write");
    } else {
      strom_completion c;
      if (strom_wait(eng, wid, &c) != 0 || c.status != 0)
        fail("mixed write status");
      strom_release(eng, wid);
    }
  }
  strom_close(eng, wfh);
  unlink(path.c_str());
}

void observer_thread(strom_engine *eng, std::atomic<bool> *stop) {
  uint64_t rd[STROM_LAT_BUCKETS], wr[STROM_LAT_BUCKETS];
  while (!stop->load(std::memory_order_acquire)) {
    strom_stats_blk st;
    strom_get_stats(eng, &st);
    if (st.requests_completed > st.requests_submitted)
      fail("completed > submitted");
    strom_pool_info pi;
    strom_get_pool_info(eng, &pi);
    if (pi.free_buffers > pi.n_buffers) fail("pool accounting");
    strom_get_latency(eng, rd, wr);
    /* per-ring counters race the hot path lock-free: completed may
     * never exceed submitted within one ring's snapshot */
    int nr = strom_ring_count(eng);
    for (int r = 0; r < nr; r++) {
      strom_ring_info ri;
      if (strom_get_ring_info(eng, (uint32_t)r, &ri) != 0) {
        fail("ring_info rc");
        continue;
      }
      if (ri.completed > ri.submitted) fail("ring completed > submitted");
      if (ri.free_buffers > ri.n_buffers) fail("ring pool accounting");
    }
    usleep(500);
  }
}

/* Multi-ring mixed-class reader: models the QoS scheduler's dispatch —
 * each thread plays one latency class pinned round-robin over a ring
 * subset (decode -> ring 0, bulk -> the rest), batches via
 * strom_submit_readv_ring racing scalar strom_submit_read_ring
 * stragglers on the SAME rings from sibling threads.  Payload verified
 * against the offset pattern: a cross-ring buffer-recycling bug shows
 * up as a mismatch, a routing bug as -EINVAL/-ENOENT failures. */
void ring_class_thread(strom_engine *eng, int fh, int iters, int seed,
                       uint32_t ring_lo, uint32_t ring_hi) {
  Rng rng(seed * 2654435761ull + 11);
  const uint32_t span = ring_hi - ring_lo + 1;
  for (int i = 0; i < iters; i++) {
    uint32_t ring = ring_lo + (uint32_t)(rng.next() % span);
    if ((i & 3) == 3) {          /* scalar straggler on the same ring */
      uint64_t off = rng.next() % (kFileBytes - 1);
      uint64_t len = 1 + rng.next() % (kMaxRead / 8);
      if (off + len > kFileBytes) len = kFileBytes - off;
      int64_t id = strom_submit_read_ring(eng, ring, fh, off, len);
      if (id < 0) { fail("submit_read_ring"); continue; }
      strom_completion c;
      if (strom_wait(eng, id, &c) != 0 || c.status != 0)
        fail("ring read status");
      else
        for (uint64_t k = 0; k < c.len; k += 997)
          if (c.data[k] != pat(off + k)) { fail("ring payload"); break; }
      strom_release(eng, id);
      continue;
    }
    const uint32_t n = 1 + (uint32_t)(rng.next() % 6);
    strom_rd_ext exts[6];
    for (uint32_t j = 0; j < n; j++) {
      uint64_t off = rng.next() % (kFileBytes - 1);
      uint64_t len = 1 + rng.next() % (kMaxRead / 4);
      if (off + len > kFileBytes) len = kFileBytes - off;
      exts[j] = strom_rd_ext{fh, 0, off, len};
    }
    int64_t ids[6];
    if (strom_submit_readv_ring(eng, ring, exts, n, ids) != 0) {
      fail("submit_readv_ring");
      continue;
    }
    for (uint32_t j = 0; j < n; j++) {
      strom_completion c;
      if (strom_wait(eng, ids[j], &c) != 0 || c.status != 0)
        fail("ring readv status");
      else {
        if (c.len != exts[j].length) fail("ring readv short");
        for (uint64_t k = 0; k < c.len; k += 997)
          if (c.data[k] != pat(exts[j].offset + k)) {
            fail("ring readv payload");
            break;
          }
      }
      strom_release(eng, ids[j]);
    }
  }
}

void churn_thread(strom_engine *eng, const std::string &path, int iters) {
  for (int i = 0; i < iters; i++) {
    int fh = strom_open(eng, path.c_str(), 0);
    if (fh < 0) { fail("churn open"); continue; }
    int64_t id = strom_submit_read(eng, fh, (uint64_t)i * 4096 % kFileBytes,
                                   4096);
    if (id >= 0) {
      strom_wait(eng, id, nullptr);
      strom_release(eng, id);
    }
    strom_close(eng, fh);
  }
}

}  // namespace

int main(int argc, char **argv) {
  int iters = argc > 1 ? atoi(argv[1]) : 300;
  int n_readers = argc > 2 ? atoi(argv[2]) : 6;
  std::string dir = argc > 3 ? argv[3] : "/tmp";

  std::string path = dir + "/stress_r.bin";
  FILE *f = fopen(path.c_str(), "wb");
  if (!f) { perror("fopen"); return 2; }
  std::vector<uint8_t> chunk(1 << 20);
  for (uint64_t off = 0; off < kFileBytes; off += chunk.size()) {
    for (size_t k = 0; k < chunk.size(); k++) chunk[k] = pat(off + k);
    fwrite(chunk.data(), 1, chunk.size(), f);
  }
  fclose(f);

  for (int use_uring = 1; use_uring >= 0; use_uring--) {
    strom_engine *eng =
        strom_engine_create(16, 8, kMaxRead + 8192, 4096, use_uring, 1);
    if (!eng) { perror("engine_create"); return 2; }
    int fh = strom_open(eng, path.c_str(), 0);
    if (fh < 0) { fprintf(stderr, "open failed\n"); return 2; }

    std::atomic<bool> stop{false};
    std::vector<std::thread> ts;
    for (int r = 0; r < n_readers; r++)
      ts.emplace_back(reader_thread, eng, fh, iters, r + 1);
    for (int r = 0; r < 2; r++)
      ts.emplace_back(readv_thread, eng, fh, iters / 2 + 1, r + 1);
    ts.emplace_back(writer_thread, eng, dir, iters / 2 + 1);
    for (int r = 0; r < 2; r++)
      ts.emplace_back(writer_burst_thread, eng, dir, iters / 2 + 1, r + 1);
    ts.emplace_back(mixed_rw_thread, eng, fh, dir, iters / 2 + 1, 1);
    ts.emplace_back(churn_thread, eng, path, iters / 2 + 1);
    std::thread obs(observer_thread, eng, &stop);
    for (auto &t : ts) t.join();
    stop.store(true, std::memory_order_release);
    obs.join();

    strom_stats_blk st;
    strom_get_stats(eng, &st);
    fprintf(stderr,
            "stress[%s]: submitted=%llu completed=%llu failed=%llu "
            "errors=%llu\n",
            use_uring ? "io_uring" : "threadpool",
            (unsigned long long)st.requests_submitted,
            (unsigned long long)st.requests_completed,
            (unsigned long long)st.requests_failed,
            (unsigned long long)g_errors.load());
    if (st.requests_failed != 0) fail("requests_failed != 0");
    strom_close(eng, fh);
    strom_engine_destroy(eng);
  }

  /* Multi-ring phase: 4 rings, mixed-class reader threads pinned the
   * way the QoS scheduler pins them (one decode-class thread owning
   * ring 0, bulk threads spread over rings 1-3), racing the writer and
   * churn paths that route round-robin across ALL rings — the
   * cross-ring file-table and pool-slice interactions TSAN must bless. */
  for (int use_uring = 1; use_uring >= 0; use_uring--) {
    strom_engine *eng = strom_engine_create_rings(
        4, 4, 4, kMaxRead + 8192, 4096, use_uring, 1);
    if (!eng) { perror("engine_create_rings"); return 2; }
    if (strom_ring_count(eng) != 4) fail("ring_count");
    /* ring routing validation is loud, not silent */
    if (strom_submit_read_ring(eng, 9, 1, 0, 4096) != -EINVAL)
      fail("bad ring index not rejected");
    int fh = strom_open(eng, path.c_str(), 0);
    if (fh < 0) { fprintf(stderr, "open failed\n"); return 2; }

    std::atomic<bool> stop{false};
    std::vector<std::thread> ts;
    ts.emplace_back(ring_class_thread, eng, fh, iters, 101, 0u, 0u);
    for (int r = 0; r < n_readers; r++)
      ts.emplace_back(ring_class_thread, eng, fh, iters, 200 + r, 1u, 3u);
    ts.emplace_back(writer_thread, eng, dir, iters / 2 + 1);
    ts.emplace_back(mixed_rw_thread, eng, fh, dir, iters / 2 + 1, 9);
    ts.emplace_back(churn_thread, eng, path, iters / 2 + 1);
    std::thread obs(observer_thread, eng, &stop);
    for (auto &t : ts) t.join();
    stop.store(true, std::memory_order_release);
    obs.join();

    strom_stats_blk st;
    strom_get_stats(eng, &st);
    uint64_t ring_sub = 0, ring_comp = 0;
    for (int r = 0; r < 4; r++) {
      strom_ring_info ri;
      strom_get_ring_info(eng, (uint32_t)r, &ri);
      ring_sub += ri.submitted;
      ring_comp += ri.completed;
      if (ri.inflight_io != 0) fail("ring inflight after drain");
    }
    if (ring_sub != st.requests_submitted) fail("ring submit accounting");
    if (ring_comp != st.requests_completed) fail("ring comp accounting");
    fprintf(stderr,
            "stress[rings=4,%s]: submitted=%llu completed=%llu "
            "failed=%llu errors=%llu\n",
            use_uring ? "io_uring" : "threadpool",
            (unsigned long long)st.requests_submitted,
            (unsigned long long)st.requests_completed,
            (unsigned long long)st.requests_failed,
            (unsigned long long)g_errors.load());
    if (st.requests_failed != 0) fail("requests_failed != 0");
    strom_close(eng, fh);
    strom_engine_destroy(eng);
  }
  /* Hot-restart phase: 2 rings; readers pin batches to both rings while
   * the main thread repeatedly wedges ring 1 (stall injection parks its
   * dispatches), hot-restarts it (parked requests cancel -ECANCELED and
   * the readers requeue them), and lets traffic resume on the rebuilt
   * ring.  TSAN must bless the restart's drain/rebuild racing live
   * submitters, waiters, and the stat observer; functionally every read
   * must end verified — cancellation is a requeue, never a loss. */
  for (int use_uring = 1; use_uring >= 0; use_uring--) {
    strom_engine *eng = strom_engine_create_rings(
        2, 4, 8, kMaxRead + 8192, 4096, use_uring, 1);
    if (!eng) { perror("engine_create_rings(restart)"); return 2; }
    if (strom_ring_restart(eng, 9, 1000000ull) != -EINVAL)
      fail("bad restart ring index not rejected");
    if (strom_set_ring_stall(eng, 9, 1) != -EINVAL)
      fail("bad stall ring index not rejected");
    int fh = strom_open(eng, path.c_str(), 0);
    if (fh < 0) { fprintf(stderr, "open failed\n"); return 2; }

    std::atomic<bool> stop{false};
    std::atomic<uint64_t> requeued{0};
    std::vector<std::thread> ts;
    for (int r = 0; r < 3; r++)
      ts.emplace_back(restart_reader_thread, eng, fh, iters, 300 + r,
                      &requeued);
    ts.emplace_back(churn_thread, eng, path, iters / 2 + 1);
    std::thread obs(observer_thread, eng, &stop);
    std::thread killer([&] {
      int restarts = 0;
      while (!stop.load(std::memory_order_acquire)) {
        strom_set_ring_stall(eng, 1, 1);
        usleep(3000);               /* let dispatches park */
        int64_t rc = strom_ring_restart(eng, 1, 500000000ull);
        if (rc < 0 && rc != -EBUSY) fail("ring_restart");
        restarts++;
        usleep(2000);               /* healthy window: traffic drains */
      }
      if (restarts < 1) fail("killer never restarted");
    });
    for (auto &t : ts) t.join();
    stop.store(true, std::memory_order_release);
    killer.join();
    obs.join();

    strom_ring_info ri;
    if (strom_get_ring_info(eng, 1, &ri) != 0) fail("ring_info(1)");
    if (ri.restarts < 1) fail("restart counter never moved");
    if (ri.parked != 0) fail("parked requests survived the phase");
    fprintf(stderr,
            "stress[restart,%s]: restarts=%llu requeued=%llu "
            "failed_comps=%llu errors=%llu\n",
            use_uring ? "io_uring" : "threadpool",
            (unsigned long long)ri.restarts,
            (unsigned long long)requeued.load(),
            (unsigned long long)ri.failed,
            (unsigned long long)g_errors.load());
    strom_stats_blk st;
    strom_get_stats(eng, &st);
    if (st.requests_failed != 0) fail("restart phase requests_failed != 0");
    if (ri.failed != 0) fail("cancels counted as ring failures");
    strom_close(eng, fh);
    strom_engine_destroy(eng);
  }
  /* Zero-copy submission phase (PR 12): SQPOLL + registered files +
   * an arena-prealloc'd staging pool, hammered by mixed read / readv /
   * write threads with a mid-run hot restart of ring 1.  The doorbell
   * elision, the slot-table updates racing open/close churn, the
   * restart's re-registration, AND the caller-owned pool must all be
   * TSAN-clean — and functionally every read still verifies. */
  setenv("STROM_SQPOLL", "1", 1);
  setenv("STROM_SQPOLL_IDLE_MS", "20", 1);
  setenv("STROM_REG_FILES", "1", 1);
  for (int use_uring = 1; use_uring >= 0; use_uring--) {
    uint64_t pool_bytes =
        strom_engine_pool_bytes(2, 8, kMaxRead + 8192, 4096);
    if (pool_bytes == 0) { fail("engine_pool_bytes"); break; }
    void *arena = strom_arena_create(pool_bytes);
    if (!arena) { perror("arena_create"); return 2; }
    strom_arena_lock(arena, pool_bytes);   /* best effort */
    strom_engine *eng = strom_engine_create_prealloc(
        2, 4, 8, kMaxRead + 8192, 4096, use_uring, 1,
        arena, pool_bytes);
    if (!eng) { perror("engine_create_prealloc"); return 2; }
    int fh = strom_open(eng, path.c_str(), 0);
    if (fh < 0) { fprintf(stderr, "open failed\n"); return 2; }

    std::atomic<bool> stop{false};
    std::atomic<uint64_t> requeued{0};
    std::vector<std::thread> ts;
    for (int r = 0; r < n_readers; r++)
      ts.emplace_back(restart_reader_thread, eng, fh, iters, 400 + r,
                      &requeued);
    /* write traffic must be restart-tolerant here: the mid-run stall
     * parks round-robin writes on ring 1 and the restart cancels them
     * for requeue — plain writer_thread would read that as damage */
    for (int r = 0; r < 2; r++)
      ts.emplace_back(restart_writer_thread, eng, dir, iters / 2 + 1,
                      50 + r);
    ts.emplace_back(churn_thread, eng, path, iters / 2 + 1);
    std::thread obs(observer_thread, eng, &stop);
    std::thread killer([&] {
      /* one mid-run restart cycle: the rebuilt uring must re-register
       * buffers + files and re-arm SQPOLL (checked below) */
      usleep(5000);
      strom_set_ring_stall(eng, 1, 1);
      usleep(3000);
      int64_t rc = strom_ring_restart(eng, 1, 500000000ull);
      if (rc < 0 && rc != -EBUSY) fail("sqpoll-phase ring_restart");
    });
    for (auto &t : ts) t.join();
    stop.store(true, std::memory_order_release);
    killer.join();
    obs.join();

    strom_ring_info ri;
    if (strom_get_ring_info(eng, 1, &ri) != 0) fail("ring_info(1)");
    /* The kernel may legitimately refuse IORING_SETUP_SQPOLL
     * (privileges pre-5.13, old kernels): the engine's documented
     * soft-fallback is a plain ring.  Only a backend that ACCEPTED the
     * mode must keep it across the restart — the worker-pool analogue
     * always does. */
    bool sq_active = ri.sqpoll == 1;
    if (!ri.backend_uring && !sq_active)
      fail("worker-pool sqpoll analogue not active after restart");
    if (!sq_active)
      fprintf(stderr, "stress[sqpoll]: note: kernel refused SQPOLL, "
                      "phase ran on the plain ring\n");
    if (ri.backend_uring && !ri.reg_files)
      fprintf(stderr, "stress[sqpoll]: note: reg_files soft-failed\n");
    strom_pool_info pi;
    strom_get_pool_info(eng, &pi);
    if (pi.pool_base != (uint64_t)(uintptr_t)arena)
      fail("prealloc pool base mismatch");
    strom_stats_blk st;
    strom_get_stats(eng, &st);
    fprintf(stderr,
            "stress[sqpoll+regfiles+arena,%s]: submitted=%llu "
            "enters=%llu elided=%llu requeued=%llu failed=%llu "
            "errors=%llu\n",
            use_uring ? "io_uring" : "threadpool",
            (unsigned long long)st.requests_submitted,
            (unsigned long long)st.submit_enters,
            (unsigned long long)st.submit_syscalls_saved,
            (unsigned long long)requeued.load(),
            (unsigned long long)st.requests_failed,
            (unsigned long long)g_errors.load());
    if (st.requests_failed != 0) fail("sqpoll phase requests_failed != 0");
    if (sq_active && st.submit_syscalls_saved == 0)
      fail("sqpoll phase elided no doorbells");
    strom_close(eng, fh);
    strom_engine_destroy(eng);
    strom_arena_destroy(arena, pool_bytes);
  }
  unsetenv("STROM_SQPOLL");
  unsetenv("STROM_SQPOLL_IDLE_MS");
  unsetenv("STROM_REG_FILES");
  unlink(path.c_str());
  unlink((dir + "/stress_w.bin").c_str());
  return g_errors.load() == 0 ? 0 : 1;
}
